"""Convex global-solve tier tests (solver/convex/).

The contracts pinned here:

- differential parity: the jit relaxation entry (f32, fixed-iteration
  projected subgradient over staged tensors) matches the float64 numpy
  reference oracle -- mass, certificate, and trace;
- lower-bound soundness: the certified LP lower bound never exceeds the
  realized FFD fleet price (choose()'s own masked-offering metric), and
  the gap denominator takes the MAX of the convex and per-class
  fractional bounds so it never loosens;
- deterministic rounding: concentration rounding conserves pods,
  respects every admitted offering's capacity, and is bit-identical
  across calls (tie-breaks come from seeding.convex_rng(), never the
  clock or ambient RNG);
- never-worse differential: tier="convex" only takes a tick on a strict
  price win with no extra unplaced pods; adversarial binpack mixes are
  a strict win, random worlds never regress;
- chaos: a failure at rpc.convex.dispatch or convex.rounding lands the
  tick on the FFD rung with decisions bit-identical to a pure-FFD
  solver and no pod lost; a "crash" action propagates (OperatorCrashed
  is a BaseException -- the rung must not swallow it);
- wire: the sidecar's solve_convex op is feature-negotiated and decides
  identically to the in-process tier; a sidecar without the feature
  degrades to the FFD rung, bit-identical;
- repack oracle: regret scoring nominates the priciest nodes first and
  the disruption sweep's stage 6 survives both an empty nomination and
  a raising oracle;
- seeding: the convex tie-break stream rides snapshot()/restore() with
  the rest of the seed fan-out.

The corpus gate on the adversarial scenario's digest + KPI dominance
lives in the sim corpus (tests/golden/scenarios/, `make sim-corpus`);
bench asserts the tick-latency overhead and gap deltas
(`make bench-convex`).
"""
import numpy as np
import pytest

from karpenter_tpu import metrics, seeding
from karpenter_tpu.apis import NodePool, Pod, TPUNodeClass
from karpenter_tpu.apis.nodeclass import SubnetStatus
from karpenter_tpu.cache.unavailable_offerings import UnavailableOfferings
from karpenter_tpu.failpoints import OperatorCrashed
from karpenter_tpu.kwok.cloud import FakeCloud
from karpenter_tpu.providers.instancetype import gen_catalog
from karpenter_tpu.providers.instancetype.offerings import OfferingsBuilder
from karpenter_tpu.providers.instancetype.provider import InstanceTypeProvider
from karpenter_tpu.providers.instancetype.types import Resolver
from karpenter_tpu.providers.pricing import PricingProvider
from karpenter_tpu.scheduling import Resources
from karpenter_tpu.solver import bound, encode, ffd
from karpenter_tpu.solver.convex import relax, rounding, tier
from karpenter_tpu.solver.convex.repack import RepackOracle
from karpenter_tpu.solver.service import TPUSolver


@pytest.fixture(scope="module")
def catalog_items():
    cloud = FakeCloud()
    prov = InstanceTypeProvider(
        cloud,
        Resolver(gen_catalog.REGION),
        OfferingsBuilder(
            PricingProvider(cloud, cloud, gen_catalog.REGION),
            UnavailableOfferings(),
            {z.name: z.zone_id for z in gen_catalog.ZONES},
        ),
        UnavailableOfferings(),
    )
    nc = TPUNodeClass("default")
    nc.status_subnets = [
        SubnetStatus(s.id, s.zone, s.zone_id) for s in cloud.describe_subnets()
    ]
    return prov.list(nc)


@pytest.fixture(scope="module")
def catalog(catalog_items):
    return encode.encode_catalog(catalog_items)


def random_pods(rng, n):
    """Seeded random world: mixed cpu/mem shapes, no constraints."""
    pods = []
    for i in range(n):
        cpu = f"{int(rng.integers(100, 4000))}m"
        mem = f"{int(rng.integers(128, 8192))}Mi"
        pods.append(Pod(f"p{i}", requests=Resources({"cpu": cpu, "memory": mem})))
    return pods


def adversarial_pods(n=30):
    """The binpack-adversarial mix (sim/scenario.py): pods sized just
    over 1/2 and 1/3 of the common node shapes -- greedy mis-ordering
    strands near-half of every node, concentration rounding does not."""
    shapes = (("1100m", "2200Mi"), ("700m", "1400Mi"), ("1700m", "3400Mi"))
    return [
        Pod(f"adv{i}", requests=Resources(
            {"cpu": shapes[i % 3][0], "memory": shapes[i % 3][1]}))
        for i in range(n)
    ]


def _world(catalog, pods, pool=None):
    """(class-set, SolveInputs, offsets, words) for direct relax calls."""
    pool = pool or NodePool("default")
    classes = encode.group_pods(pods, extra_requirements=pool.requirements())
    cs = encode.encode_classes(classes, catalog)
    inp, offsets, words = ffd.make_inputs(catalog, cs)
    return cs, inp, offsets, words


def _canon(result):
    """Canonical form of a SchedulingResult for bit-identity checks:
    existing assignments, unschedulable reasons, and the multiset of
    (instance-type names, sorted member pods) per new group."""
    groups = sorted(
        (
            tuple(it.name for it in g.instance_types),
            tuple(sorted(p.name for p in g.pods)),
        )
        for g in result.new_groups
    )
    return (
        tuple(sorted(result.existing_assignments.items())),
        tuple(sorted(result.unschedulable.items())),
        tuple(groups),
    )


def _pods_accounted(result, pods):
    placed = sum(len(g.pods) for g in result.new_groups)
    placed += len(result.existing_assignments)
    return placed + len(result.unschedulable) == len(pods)


# -- relaxation: device vs reference ------------------------------------------


class TestRelaxParity:
    @pytest.mark.parametrize("seed", [0, 3])
    def test_device_matches_reference(self, catalog, seed):
        rng = np.random.default_rng(seed)
        pods = random_pods(rng, int(rng.integers(30, 90)))
        cs, inp, offsets, words = _world(catalog, pods)
        x_ref, lower_ref, trace_ref = relax.reference_relax(catalog, cs)
        out = relax.convex_relax(
            inp, iters=relax.DEFAULT_ITERS, word_offsets=offsets, words=words)
        x_dev, lower_dev, trace_dev = relax.fetch_relax(out)
        # f32 device vs f64 reference: observed divergence ~1e-7; the
        # tolerance leaves an order of magnitude of headroom
        np.testing.assert_allclose(x_dev, x_ref, atol=5e-5)
        assert abs(lower_dev - lower_ref) <= 5e-5 * max(lower_ref, 1.0)
        np.testing.assert_allclose(trace_dev, trace_ref, atol=5e-5)

    def test_mass_conservation(self, catalog):
        rng = np.random.default_rng(1)
        pods = random_pods(rng, 50)
        cs, _, _, _ = _world(catalog, pods)
        x, _, _ = relax.reference_relax(catalog, cs)
        counts = np.asarray(cs.count, dtype=np.float64)
        # every class's fractional mass sums to its pod count (padded
        # rows have count 0 and stay at 0)
        np.testing.assert_allclose(x.sum(axis=-1), counts, atol=1e-6)
        assert (x >= -1e-9).all()

    def test_iterations_to_convergence(self, catalog):
        rng = np.random.default_rng(2)
        pods = random_pods(rng, 40)
        cs, _, _, _ = _world(catalog, pods)
        _, _, trace = relax.reference_relax(catalog, cs)
        it = relax.iterations_to_convergence(trace)
        assert 1 <= it <= relax.DEFAULT_ITERS


# -- lower bound: soundness + gap denominator ---------------------------------


class TestLowerBound:
    @pytest.mark.parametrize("seed", [0, 1, 2, 3, 4, 5])
    def test_sound_below_ffd_price(self, catalog, seed):
        rng = np.random.default_rng(seed)
        pods = random_pods(rng, int(rng.integers(25, 110)))
        cs, inp, offsets, words = _world(catalog, pods)
        dense_ffd = ffd.solve_dense_tuple(
            inp, g_max=64, word_offsets=offsets, words=words)
        p_ffd = tier.dense_price(dense_ffd, np.asarray(catalog.price))
        _, lower, _ = relax.reference_relax(catalog, cs)
        assert lower <= p_ffd + 1e-6, (
            f"certified lower bound {lower} exceeds realized FFD price {p_ffd}")

    def test_tightens_fractional_bound_somewhere(self, catalog):
        """The coupled relaxation strictly tightens the per-class
        fractional bound on SOME instances; on others the fixed-
        iteration certificate is looser -- which is exactly why
        _finish_quality takes the max of the two. Both facts pinned."""
        tightened = False
        for seed in range(10):
            rng = np.random.default_rng(seed)
            pods = random_pods(rng, int(rng.integers(25, 110)))
            cs, _, _, _ = _world(catalog, pods)
            _, lower, _ = relax.reference_relax(catalog, cs)
            b, _ = bound.reference_bound(
                catalog, cs, np.asarray(cs.count, dtype=np.float64))
            combined = max(b, lower)
            assert combined >= b - 1e-12  # the denominator never loosens
            if lower > b * (1.0 + 1e-6):
                tightened = True
        assert tightened, "convex LB never tightened the fractional bound"

    def test_solver_publishes_gap_and_lower(self, catalog_items):
        solver = TPUSolver(g_max=64, tier="convex")
        rng = np.random.default_rng(0)
        res = solver.solve(NodePool("default"), catalog_items,
                           random_pods(rng, 24))
        assert not res.unschedulable
        lc = solver.last_convex
        assert lc and lc["winner"] in ("convex", "ffd")
        assert lc["lower"] > 0.0
        assert 1 <= lc["iterations"] <= relax.DEFAULT_ITERS
        assert solver.last_quality["optimality_gap"] >= 1.0 - 1e-9


# -- deterministic rounding ---------------------------------------------------


class TestRounding:
    def test_assign_types_concentrates(self):
        price_ck = np.array([[3.0, 1.0, 2.0], [0.5, 9.0, 9.0]])
        fit0 = np.array([[1.0, 2.0, 1.0], [1.0, 1.0, 1.0]])
        feas = np.ones((2, 3), dtype=bool)
        x = np.zeros((2, 3))
        count = np.array([7, 4])
        n = rounding.assign_types(x, feas, count, price_ck=price_ck, fit0=fit0)
        # class 0: amortized cost argmin is k=1 (1.0/2); class 1: k=0
        assert n[0, 1] == 7 and n[1, 0] == 4
        assert n.sum() == count.sum()
        assert (n >= 0).all()
        # all mass on exactly one type per class
        assert ((n > 0).sum(axis=-1) == 1).all()

    def test_assign_types_seeded_tiebreak(self):
        # two identical offerings: the tie-break must be the seeded
        # stream, deterministic under the same applied seed
        price_ck = np.array([[1.0, 1.0]])
        fit0 = np.ones((1, 2))
        feas = np.ones((1, 2), dtype=bool)
        x = np.zeros((1, 2))
        count = np.array([5])
        token = seeding.snapshot()
        try:
            seeding.apply(77)
            a = rounding.assign_types(
                x, feas, count, price_ck=price_ck, fit0=fit0)
            b = rounding.assign_types(
                x, feas, count, price_ck=price_ck, fit0=fit0)
            np.testing.assert_array_equal(a, b)
        finally:
            seeding.restore(token)

    def test_round_solution_feasible_and_deterministic(self, catalog):
        rng = np.random.default_rng(4)
        pods = random_pods(rng, 60)
        cs, _, _, _ = _world(catalog, pods)
        x, _, _ = relax.reference_relax(catalog, cs)
        dense = rounding.round_solution(x, catalog, cs, g_max=64)
        assert dense is not None
        again = rounding.round_solution(x, catalog, cs, g_max=64)
        for a, b in zip(dense, again):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        take, unplaced, n_open, gmask, gzone, gcap = (
            np.asarray(t) for t in dense)
        counts = np.asarray(cs.count)
        # conservation: every pod placed or explicitly left behind
        np.testing.assert_array_equal(take.sum(axis=-1) + unplaced, counts)
        # every open group names at least one admitted type/zone/captype,
        # and its load fits EVERY admitted type's effective capacity
        cap_eff = np.maximum(
            np.asarray(catalog.cap) - np.asarray(cs.node_overhead)[None, :],
            0.0)
        req = np.asarray(cs.req, dtype=np.float64)
        for g in range(int(n_open)):
            assert gmask[g].any() and gzone[g].any() and gcap[g].any()
            load = (take[:, g].astype(np.float64)[:, None] * req).sum(axis=0)
            for k in np.flatnonzero(gmask[g]):
                assert (load <= cap_eff[k] + 1e-6).all(), (
                    f"group {g} overflows admitted type {k}")


# -- the differential: never worse than FFD -----------------------------------


class TestDifferential:
    def test_convex_wins_adversarial(self, catalog_items):
        solver = TPUSolver(g_max=64, tier="convex")
        res = solver.solve(
            NodePool("default"), catalog_items, adversarial_pods(30))
        assert not res.unschedulable
        lc = solver.last_convex
        assert lc["winner"] == "convex", lc
        assert lc["price_convex"] < lc["price_ffd"], lc

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_never_worse_on_random_worlds(self, catalog_items, seed):
        rng = np.random.default_rng(seed)
        pods = random_pods(rng, int(rng.integers(20, 70)))
        cx = TPUSolver(g_max=64, tier="convex")
        res_cx = cx.solve(NodePool("default"), catalog_items, pods)
        ffd_solver = TPUSolver(g_max=64)
        res_ffd = ffd_solver.solve(NodePool("default"), catalog_items, pods)
        lc = cx.last_convex
        chosen = (lc["price_convex"] if lc["winner"] == "convex"
                  else lc["price_ffd"])
        # choose()'s own masked-offering metric: the tick's price never
        # exceeds FFD's (realized_per_h is a different estimator and is
        # NOT comparable across tiers)
        assert chosen <= lc["price_ffd"] * (1.0 + 1e-9), lc
        assert len(res_cx.unschedulable) <= len(res_ffd.unschedulable)
        assert _pods_accounted(res_cx, pods)

    def test_convex_deterministic(self, catalog_items):
        pods = adversarial_pods(24)
        canons = set()
        for _ in range(2):
            solver = TPUSolver(g_max=64, tier="convex")
            canons.add(_canon(solver.solve(
                NodePool("default"), catalog_items, pods)))
        assert len(canons) == 1, "convex tier decisions are not deterministic"

    def test_tier_validation(self):
        with pytest.raises(ValueError):
            TPUSolver(tier="simplex")


# -- chaos: the FFD rung ------------------------------------------------------


class TestChaosRungs:
    @pytest.mark.parametrize("site,reason", [
        ("rpc.convex.dispatch", "dispatch"),
        ("convex.rounding", "rounding"),
    ])
    def test_failure_lands_on_ffd_rung(self, catalog_items, failpoints,
                                       site, reason):
        """A mid-solve convex failure degrades to the incumbent: the
        tick's decisions are bit-identical to a pure-FFD solver's and
        every pod is accounted for."""
        pods = adversarial_pods(21)
        pure = TPUSolver(g_max=64)
        want = _canon(pure.solve(NodePool("default"), catalog_items, pods))
        before = metrics.CONVEX_FALLBACKS.value(reason=reason)
        failpoints.arm(site, "error", "RuntimeError", times=8)
        cx = TPUSolver(g_max=64, tier="convex")
        res = cx.solve(NodePool("default"), catalog_items, pods)
        assert _canon(res) == want, (
            f"{site} failure changed decisions vs pure FFD")
        assert _pods_accounted(res, pods)
        assert metrics.CONVEX_FALLBACKS.value(reason=reason) > before

    def test_crash_action_propagates(self, catalog_items, failpoints):
        """OperatorCrashed is a BaseException: the rounding rung's
        except-Exception guard must NOT swallow a simulated crash."""
        failpoints.arm("convex.rounding", "crash", times=1)
        cx = TPUSolver(g_max=64, tier="convex")
        with pytest.raises(OperatorCrashed):
            cx.solve(NodePool("default"), catalog_items, adversarial_pods(9))


# -- wire: the sidecar's solve_convex op --------------------------------------


class TestWire:
    def _rig(self, tmp_path):
        from karpenter_tpu.solver.rpc import SolverClient, SolverServer

        path = str(tmp_path / "solver.sock")
        srv = SolverServer(path=path).start()
        client = SolverClient(path=path)
        return srv, client

    def test_wire_matches_local(self, tmp_path, catalog_items):
        srv, client = self._rig(tmp_path)
        try:
            assert "convex" in client.features()
            pods = adversarial_pods(18)
            remote = TPUSolver(g_max=64, client=client, tier="convex")
            res_r = remote.solve(NodePool("default"), catalog_items, pods)
            local = TPUSolver(g_max=64, tier="convex")
            res_l = local.solve(NodePool("default"), catalog_items, pods)
            assert _canon(res_r) == _canon(res_l)
            assert remote.last_convex["winner"] == local.last_convex["winner"]
        finally:
            client.close()
            srv.stop()

    def test_sidecar_without_feature_degrades(self, tmp_path, catalog_items):
        """An old sidecar (no `convex` feature) keeps the tick: the
        client falls back to the plain solve op, decisions bit-identical
        to pure FFD, and the fallback is counted."""
        srv, client = self._rig(tmp_path)
        try:
            feats = frozenset(f for f in client.features() if f != "convex")
            client.features = lambda: feats  # simulate an old sidecar
            pods = adversarial_pods(15)
            pure = TPUSolver(g_max=64)
            want = _canon(pure.solve(NodePool("default"), catalog_items, pods))
            before = metrics.CONVEX_FALLBACKS.value(reason="wire")
            remote = TPUSolver(g_max=64, client=client, tier="convex")
            res = remote.solve(NodePool("default"), catalog_items, pods)
            assert _canon(res) == want
            assert metrics.CONVEX_FALLBACKS.value(reason="wire") > before
        finally:
            client.close()
            srv.stop()


# -- repack oracle ------------------------------------------------------------


class _FakeCandidate:
    def __init__(self, pods, price):
        self.pods = pods
        self.price = price


class TestRepackOracle:
    def test_propose_ranks_regret(self, catalog_items):
        pod = Pod("r0", requests=Resources({"cpu": "200m", "memory": "256Mi"}))
        cheap = _FakeCandidate([pod], price=0.001)
        pricey = _FakeCandidate(
            [Pod("r1", requests=Resources({"cpu": "300m", "memory": "256Mi"}))],
            price=40.0)
        mid = _FakeCandidate(
            [Pod("r2", requests=Resources({"cpu": "250m", "memory": "256Mi"}))],
            price=5.0)
        oracle = RepackOracle()
        sets = oracle.propose(
            [cheap, pricey, mid], [NodePool("default")],
            {"default": catalog_items})
        assert sets, "overpriced nodes produced no nominations"
        assert sets[0] == (1,), "top singleton is not the max-regret node"
        assert all(all(0 <= i < 3 for i in s) for s in sets)
        assert (1, 2) in sets, "top-regret pair missing"
        assert oracle.last_regret is not None
        assert oracle.last_regret[1] > oracle.last_regret[2] > 0.0
        assert oracle.last_lower > 0.0

    def test_propose_empty_inputs(self, catalog_items):
        oracle = RepackOracle()
        assert oracle.propose([], [NodePool("default")],
                              {"default": catalog_items}) == []
        pod = Pod("r0", requests=Resources({"cpu": "200m"}))
        cand = _FakeCandidate([pod], price=10.0)
        assert oracle.propose([cand], [NodePool("default")], None) == []
        assert oracle.propose([cand], [NodePool("default")], {}) == []

    def test_stage6_rides_disruption_sweep(self):
        """The controller runs stage 6 with a live oracle: the sweep
        completes, and a RAISING oracle is tolerated (logged, skipped)
        without dropping the tick or a pod."""
        from karpenter_tpu.apis import NodeClaim
        from karpenter_tpu.cache.ttl import FakeClock
        from karpenter_tpu.controllers.disruption import (
            DisruptionController, MIN_NODE_LIFETIME)
        from karpenter_tpu.operator import Operator

        op = Operator(clock=FakeClock(100_000.0))
        op.cluster.create(TPUNodeClass("default"))
        op.cluster.create(NodePool("default"))
        oracle = RepackOracle()
        op.disruption = DisruptionController(
            op.cluster, op.cloud_provider, op.pricing,
            op.options.feature_gates, recorder=op.recorder, repack=oracle)
        pods = [Pod(f"p{i}", requests=Resources(
            {"cpu": "1500m", "memory": "2Gi"})) for i in range(2)]
        op.cluster.create(pods[0])
        op.settle(max_ticks=30)
        op.cluster.create(pods[1])
        op.settle(max_ticks=30)
        assert not op.cluster.pending_pods()
        op.clock.step(MIN_NODE_LIFETIME + 60)
        decisions = op.disruption.reconcile()
        # nominations (if any) were judged by the same simulate/price
        # differential as stages 1-5: no pod may be stranded by a verdict
        assert not op.cluster.pending_pods()
        assert isinstance(decisions, list)
        # a raising oracle degrades to stages 1-5, never into the tick
        oracle_boom = RepackOracle()
        oracle_boom.propose = lambda *a, **k: (_ for _ in ()).throw(
            RuntimeError("oracle down"))
        op.disruption.repack = oracle_boom
        assert isinstance(op.disruption.reconcile(), list)
        assert len(op.cluster.list(NodeClaim)) >= 0  # sweep survived


# -- seeding ------------------------------------------------------------------


class TestSeeding:
    def test_convex_rng_fresh_and_seeded(self):
        token = seeding.snapshot()
        try:
            seeding.apply(123)
            a = [seeding.convex_rng().random() for _ in range(3)]
            b = [seeding.convex_rng().random() for _ in range(3)]
            # fresh per call ON PURPOSE: every rounding pass restarts the
            # stream so a tick's tie-breaks are replayable in isolation
            assert a == b
            expect = seeding.seeded_rng("convex", 123)
            assert a[0] == expect.random()
            seeding.apply(124)
            assert seeding.convex_rng().random() != a[0]
        finally:
            seeding.restore(token)

    def test_snapshot_restore_roundtrip(self):
        token = seeding.snapshot()
        prior = seeding._convex_seed
        try:
            seeding.apply(999)
            assert seeding._convex_seed == 999
        finally:
            seeding.restore(token)
        assert seeding._convex_seed == prior
