"""Circuit-breaker tests: state machine, jittered backoff, supervised
recovery (probe + catalog re-stage), TPUSolver integration (instant CPU
fallback with identical decisions), and the provisioner's synchronous
ticking while the breaker is open."""
import time

import pytest

from karpenter_tpu.apis import NodePool, Pod, TPUNodeClass
from karpenter_tpu.cache.ttl import FakeClock
from karpenter_tpu.scheduling import Resources
from karpenter_tpu.solver.breaker import CLOSED, HALF_OPEN, OPEN, CircuitBreaker
from karpenter_tpu.solver.rpc import SolverClient, SolverServer
from karpenter_tpu.solver.service import TPUSolver


def _signature(result):
    return (
        sorted((len(g.pods), g.instance_types[0].name) for g in result.new_groups),
        sorted(result.unschedulable),
        sorted(result.existing_assignments.items()),
    )


@pytest.fixture(scope="module")
def catalog_items():
    from karpenter_tpu.apis.nodeclass import SubnetStatus
    from karpenter_tpu.cache.unavailable_offerings import UnavailableOfferings
    from karpenter_tpu.kwok.cloud import FakeCloud
    from karpenter_tpu.providers.instancetype import gen_catalog
    from karpenter_tpu.providers.instancetype.offerings import OfferingsBuilder
    from karpenter_tpu.providers.instancetype.provider import InstanceTypeProvider
    from karpenter_tpu.providers.instancetype.types import Resolver
    from karpenter_tpu.providers.pricing import PricingProvider

    cloud = FakeCloud()
    prov = InstanceTypeProvider(
        cloud,
        Resolver(gen_catalog.REGION),
        OfferingsBuilder(
            PricingProvider(cloud, cloud, gen_catalog.REGION),
            UnavailableOfferings(),
            {z.name: z.zone_id for z in cloud.describe_zones()},
        ),
        UnavailableOfferings(),
    )
    nc = TPUNodeClass("default")
    nc.status_subnets = [
        SubnetStatus(s.id, s.zone, s.zone_id) for s in cloud.describe_subnets()
    ]
    return prov.list(nc)


def make_pods(n, cpu="500m", mem="1Gi"):
    return [Pod(f"p{i}", requests=Resources({"cpu": cpu, "memory": mem})) for i in range(n)]


class TestStateMachine:
    def test_trips_after_k_consecutive_failures(self):
        b = CircuitBreaker(failure_threshold=3, rng=lambda: 0.0)
        assert b.allow()
        assert b.record_failure() is False
        assert b.record_failure() is False
        assert b.record_failure() is True
        assert b.state == OPEN and not b.allow()
        assert b.trips == 1

    def test_success_resets_the_consecutive_count(self):
        b = CircuitBreaker(failure_threshold=2, rng=lambda: 0.0)
        b.record_failure()
        b.record_success()
        assert b.record_failure() is False, "success must reset the streak"
        assert b.state == CLOSED

    def test_probe_failure_doubles_backoff_with_jitter_cap(self):
        clk = FakeClock(100.0)
        b = CircuitBreaker(
            failure_threshold=1, backoff_base=1.0, backoff_max=4.0,
            probe=lambda: False, clock=clk.now, rng=lambda: 0.5,
        )
        b.record_failure()
        d = b.describe()
        # jitter factor with rng=0.5 is 1.25
        assert d["next_probe_in_s"] == pytest.approx(1.25)
        clk.step(2.0)
        assert b.maybe_probe() is False
        assert b.describe()["backoff_s"] == pytest.approx(2.0)
        clk.step(10.0)
        b.maybe_probe()
        clk.step(10.0)
        b.maybe_probe()
        assert b.describe()["backoff_s"] == pytest.approx(4.0), "capped"
        assert b.probes_failed == 3

    def test_probe_not_due_does_not_run(self):
        clk = FakeClock(0.0)
        calls = []
        b = CircuitBreaker(
            failure_threshold=1, backoff_base=10.0,
            probe=lambda: calls.append(1) or True, clock=clk.now, rng=lambda: 0.0,
        )
        b.record_failure()
        assert b.maybe_probe() is False and not calls
        clk.step(11.0)
        assert b.maybe_probe() is True and len(calls) == 1
        assert b.state == CLOSED

    def test_promotion_runs_on_promote_before_traffic_reenters(self):
        order = []
        b = CircuitBreaker(
            failure_threshold=1, probe=lambda: True,
            on_promote=lambda: order.append(("promote", b.allow())),
            rng=lambda: 0.0,
        )
        b.record_failure()
        assert b.probe_now() is True
        # the re-stage hook observed allow() still False: no solve can race
        # onto the wire before the stale connection is dropped
        assert order == [("promote", False)]
        assert b.allow() and b.promotions == 1

    def test_describe_fields(self):
        b = CircuitBreaker(failure_threshold=2, rng=lambda: 0.0)
        d = b.describe()
        assert d["state"] == CLOSED and d["open_for_s"] is None
        b.record_failure()
        b.record_failure()
        d = b.describe()
        assert d["state"] == OPEN
        assert d["consecutive_failures"] == 2
        assert d["next_probe_in_s"] is not None

    def test_half_open_rejects_regular_traffic(self):
        import threading

        started = threading.Event()
        release = threading.Event()

        def probe():
            started.set()
            release.wait(timeout=5.0)
            return True

        b = CircuitBreaker(failure_threshold=1, probe=probe, rng=lambda: 0.0,
                           backoff_base=0.0)
        b.record_failure()
        t = threading.Thread(target=b.probe_now, daemon=True)
        t.start()
        assert started.wait(timeout=5.0)
        assert b.state == HALF_OPEN and not b.allow()
        release.set()
        t.join(timeout=5.0)
        assert b.state == CLOSED


class TestSolverIntegration:
    def test_dead_sidecar_degrades_then_short_circuits(self, catalog_items, failpoints):
        """The acceptance shape: sidecar down -> the first K ticks pay the
        bounded connect failure and fall back to the CPU path; the breaker
        opens; subsequent ticks never touch the socket and complete fast.
        Decisions are identical throughout."""
        from karpenter_tpu import metrics

        pool = NodePool("default")
        pods = make_pods(12)
        ref = TPUSolver(g_max=64)
        want = _signature(ref.solve(pool, catalog_items, list(pods)))

        client = SolverClient(path="/tmp/karpenter-breaker-test-no-such.sock",
                              connect_timeout=0.2)
        breaker = CircuitBreaker(failure_threshold=2, backoff_base=1000.0)
        s = TPUSolver(g_max=64, client=client, breaker=breaker)
        # count connect ATTEMPTS without changing behavior (latency 0)
        failpoints.arm("rpc.client.connect", "latency", "0")

        assert _signature(s.solve(pool, catalog_items, list(pods))) == want
        assert breaker.state == CLOSED
        assert _signature(s.solve(pool, catalog_items, list(pods))) == want
        assert breaker.state == OPEN
        attempts_before = failpoints.hits("rpc.client.connect")
        t0 = time.perf_counter()
        assert _signature(s.solve(pool, catalog_items, list(pods))) == want
        wall = time.perf_counter() - t0
        assert failpoints.hits("rpc.client.connect") == attempts_before, (
            "an open breaker must not attempt any connection"
        )
        assert wall < 2.0, f"breaker-open tick stalled: {wall:.2f}s"
        assert metrics.BREAKER_SHORT_CIRCUITS.value() >= 1

    def test_supervised_recovery_restages_and_repromotes(self, catalog_items, tmp_path):
        """Sidecar comes back: probe_now() promotes, the promotion hook
        drops the connection, and the next solve re-stages on the fresh
        sidecar and returns over the wire -- identical decisions before,
        during, and after the outage."""
        pool = NodePool("default")
        pods = make_pods(9)
        ref = TPUSolver(g_max=64)
        want = _signature(ref.solve(pool, catalog_items, list(pods)))

        path = str(tmp_path / "solver.sock")
        srv = SolverServer(path=path).start()
        try:
            client = SolverClient(path=path, connect_timeout=0.3)
            breaker = CircuitBreaker(failure_threshold=1, backoff_base=1000.0)
            s = TPUSolver(g_max=64, client=client, breaker=breaker)
            assert _signature(s.solve(pool, catalog_items, list(pods))) == want
            assert client._staged_seqnums, "healthy path staged on the sidecar"

            # outage: kill the sidecar. stop() only closes the LISTENER
            # (handler threads are daemons); a real process death also
            # severs the live connection, which close() models here.
            srv.stop()
            client.close()
            assert _signature(s.solve(pool, catalog_items, list(pods))) == want
            assert breaker.state == OPEN
            assert breaker.probe_now() is False, "probe against a dead sidecar fails"
            assert breaker.state == OPEN

            # recovery: a NEW sidecar process on the same path
            srv = SolverServer(path=path).start()
            assert breaker.probe_now() is True
            assert breaker.state == CLOSED
            assert not client._staged_seqnums, (
                "promotion must clear staging so the fresh sidecar re-stages"
            )
            assert _signature(s.solve(pool, catalog_items, list(pods))) == want
            assert client._staged_seqnums, "post-promotion solve re-staged over the wire"
            assert breaker.state == CLOSED
        finally:
            srv.stop()

    def test_wire_healthy_gates_the_pipelined_tick(self, catalog_items):
        """The provisioner keeps ticking SYNCHRONOUSLY while the breaker
        is open: wire_healthy() is False, so the double-buffered dispatch
        never engages and every decision applies in its own tick."""
        from karpenter_tpu.operator import Operator

        client = SolverClient(path="/tmp/karpenter-breaker-test-no-such.sock",
                              connect_timeout=0.2)
        breaker = CircuitBreaker(failure_threshold=1, backoff_base=1000.0)
        s = TPUSolver(g_max=64, client=client, breaker=breaker)
        assert s.wire_healthy()
        op = Operator(clock=FakeClock(1.0), solver=s)
        op.cluster.create(TPUNodeClass("default"))
        op.cluster.create(NodePool("default"))
        for i in range(8):
            op.cluster.create(Pod(f"w{i}", requests=Resources({"cpu": "500m", "memory": "1Gi"})))
        op.settle(max_ticks=20)
        assert not op.cluster.pending_pods(), "degraded rig still provisions"
        assert breaker.state == OPEN
        assert not s.wire_healthy()
        assert op.provisioner._inflight is None, (
            "breaker open -> no pipelined dispatch may be left in flight"
        )

    def test_health_endpoints_expose_breaker_state(self):
        """/debug/breaker serves the full state document (loopback-only)
        and /healthz carries the state line without changing liveness."""
        import json
        import urllib.request

        from karpenter_tpu.operator.health import HealthServer

        b = CircuitBreaker(failure_threshold=1, backoff_base=1000.0, rng=lambda: 0.0)
        srv = HealthServer(port=0).start()
        srv.breaker_info = b.describe
        try:
            srv.beat_loop()
            doc = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/breaker", timeout=10).read())
            assert doc["state"] == CLOSED
            b.record_failure()
            doc = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/breaker", timeout=10).read())
            assert doc["state"] == OPEN and doc["trips"] == 1
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz", timeout=10).read().decode()
            assert "solver-wire-breaker: open" in body, (
                "an open breaker is degraded-but-ALIVE: state in the body, status 200"
            )
        finally:
            srv.stop()

    def test_debug_breaker_without_wire_reports_unconfigured(self):
        import json
        import urllib.request

        from karpenter_tpu.operator.health import HealthServer

        srv = HealthServer(port=0).start()
        try:
            doc = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/breaker", timeout=10).read())
            assert doc == {"configured": False}
        finally:
            srv.stop()

    def test_breaker_false_disables(self):
        s = TPUSolver(g_max=64, client=object(), breaker=False)
        assert s.breaker is None
        assert s.wire_healthy()

    def test_default_breaker_is_self_recovering(self):
        """A TPUSolver-created breaker must carry its own probe driver
        (auto_probe): an embedder that never calls maybe_probe() would
        otherwise stay on the CPU path forever after one transient
        outage."""
        s = TPUSolver(g_max=64, client=object())
        assert s.breaker is not None
        assert s.breaker.auto_probe is True
        assert s.breaker._probe is not None and s.breaker._on_promote is not None

    def test_in_process_solver_has_no_breaker(self):
        s = TPUSolver(g_max=64)
        assert s.breaker is None and s.wire_healthy()
