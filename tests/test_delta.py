"""Differential suite for the incremental delta-solve engine.

The tentpole contract: every layer of the incremental tick — the
cross-tick grouping cache (encode.IncrementalGrouper), the per-class
encode row cache (encode_classes row_cache), and the delta class
shipping over the wire (solver/rpc.py solve_delta) — must be
BYTE-IDENTICAL to the full re-encode path. Property-style seeded churn
sequences drive grouping/encode/wire differentials; the committed sim
corpus replays through the delta backend against the golden digests.
"""
import json
import os

import numpy as np
import pytest

from karpenter_tpu import metrics
from karpenter_tpu.apis import NodePool, Pod, TPUNodeClass, labels as wk
from karpenter_tpu.scheduling import Resources, Taint, Toleration
from karpenter_tpu.solver import encode
from karpenter_tpu.solver.rpc import SolverClient, SolverServer, StaleEpochError
from karpenter_tpu.solver.service import TPUSolver

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "golden", "scenarios")


@pytest.fixture(scope="module")
def server():
    srv = SolverServer(insecure_tcp=True).start()
    yield srv
    srv.stop()


@pytest.fixture()
def client(server):
    c = SolverClient(server.address[0], server.address[1], delta=True)
    yield c
    c.close()


@pytest.fixture(scope="module")
def catalog_items():
    from karpenter_tpu.apis.nodeclass import SubnetStatus
    from karpenter_tpu.cache.unavailable_offerings import UnavailableOfferings
    from karpenter_tpu.kwok.cloud import FakeCloud
    from karpenter_tpu.providers.instancetype import gen_catalog
    from karpenter_tpu.providers.instancetype.offerings import OfferingsBuilder
    from karpenter_tpu.providers.instancetype.provider import InstanceTypeProvider
    from karpenter_tpu.providers.instancetype.types import Resolver
    from karpenter_tpu.providers.pricing import PricingProvider

    cloud = FakeCloud()
    prov = InstanceTypeProvider(
        cloud,
        Resolver(gen_catalog.REGION),
        OfferingsBuilder(
            PricingProvider(cloud, cloud, gen_catalog.REGION),
            UnavailableOfferings(),
            {z.name: z.zone_id for z in cloud.describe_zones()},
        ),
        UnavailableOfferings(),
    )
    nc = TPUNodeClass("default")
    nc.status_subnets = [SubnetStatus(s.id, s.zone, s.zone_id) for s in cloud.describe_subnets()]
    return prov.list(nc)


def churn_pods(rng: np.random.Generator, tick: int, n: int = 60):
    """One tick's pending set from a small template universe: the same
    structural classes recur across ticks while names and counts churn."""
    shapes = [
        ("250m", "512Mi", None, ()),
        ("500m", "1Gi", None, ()),
        ("1", "2Gi", {wk.CAPACITY_TYPE_LABEL: wk.CAPACITY_TYPE_ON_DEMAND}, ()),
        ("2", "4Gi", {wk.ARCH_LABEL: "arm64"}, ()),
        ("500m", "2Gi", None, (Toleration(key="dedicated", operator="Exists"),)),
    ]
    pods = []
    for i in range(n):
        t = int(rng.integers(0, len(shapes)))
        cpu, mem, sel, tol = shapes[t]
        pods.append(Pod(
            f"churn-{tick}-{i}",
            requests=Resources({"cpu": cpu, "memory": mem}),
            node_selector=dict(sel) if sel else {},
            tolerations=list(tol),
        ))
    return pods


def decision_sig(res):
    return (
        sorted(
            (tuple(sorted(p.metadata.name for p in g.pods)), g.instance_types[0].name)
            for g in res.new_groups
        ),
        sorted(res.existing_assignments.items()),
        sorted(res.unschedulable.items()),
    )


def classes_sig(classes):
    """Everything downstream reads from a grouping result."""
    return [
        (
            pc.key,
            [p.metadata.name for p in pc.pods],
            pc.requests.tobytes(),
            pc.requirements.stable_hash(),
            pc.has_affinity, pc.multi_node_affinity, pc.has_preferences,
            pc.env_count,
        )
        for pc in classes
    ]


class TestIncrementalGrouper:
    def test_matches_group_pods_over_seeded_churn(self):
        rng = np.random.default_rng(7)
        grouper = encode.IncrementalGrouper()
        for tick in range(8):
            n = int(rng.integers(20, 90))
            pods = churn_pods(rng, tick, n)
            assert classes_sig(grouper.group(pods)) == classes_sig(encode.group_pods(pods))

    def test_stats_track_churn(self):
        grouper = encode.IncrementalGrouper()
        pods = churn_pods(np.random.default_rng(1), 0, 40)
        grouper.group(pods)
        assert grouper.last_stats["full_rebuild"] is True
        # identical structural tick (fresh names, same mix): counts equal
        grouper.group(churn_pods(np.random.default_rng(1), 1, 40))
        st = grouper.last_stats
        assert st["full_rebuild"] is False
        assert st["dirty_classes"] == 0 and st["dirty_fraction"] == 0.0
        # shifted mix: some class counts change
        grouper.group(churn_pods(np.random.default_rng(2), 2, 47))
        assert grouper.last_stats["dirty_fraction"] > 0.0

    def test_routing_flags_follow_live_pods(self):
        from karpenter_tpu.apis.pod import PodAffinityTerm

        grouper = encode.IncrementalGrouper()
        plain = [Pod("p0", requests=Resources({"cpu": "1", "memory": "1Gi"}))]
        aff = [Pod(
            "a0", requests=Resources({"cpu": "1", "memory": "1Gi"}),
            labels={"tier": "x"},
            affinity_terms=[PodAffinityTerm(
                label_selector={"tier": "x"}, topology_key=wk.HOSTNAME_LABEL)],
        )]
        out = grouper.group(plain + aff)
        assert [pc.has_affinity for pc in out] == [
            pc.has_affinity for pc in encode.group_pods(plain + aff)
        ]
        # the affinity pod leaves: no stale suffix class survives
        out = grouper.group(plain)
        assert len(out) == 1 and not out[0].has_affinity

    def test_fresh_podclass_objects_per_call(self):
        """Pipelined tickets own their class lists: a later group() call
        must never mutate a previously returned class."""
        grouper = encode.IncrementalGrouper()
        first = grouper.group(churn_pods(np.random.default_rng(3), 0, 30))
        names = [[p.metadata.name for p in pc.pods] for pc in first]
        grouper.group(churn_pods(np.random.default_rng(4), 1, 50))
        assert names == [[p.metadata.name for p in pc.pods] for pc in first]


class TestEncodeRowCache:
    def _encode_pair(self, classes, catalog, cache, taints=()):
        with_cache = encode.encode_classes(
            classes, catalog, pool_taints=taints, row_cache=cache)
        without = encode.encode_classes(classes, catalog, pool_taints=taints)
        return with_cache, without

    def test_cached_rows_byte_identical_over_churn(self, catalog_items):
        catalog = encode.encode_catalog(catalog_items)
        cache = {}
        rng = np.random.default_rng(11)
        taints = (Taint("dedicated", "NoSchedule", "x"),)
        for tick in range(5):
            classes = encode.group_pods(churn_pods(rng, tick, int(rng.integers(20, 70))))
            a, b = self._encode_pair(classes, catalog, cache, taints=taints)
            for name in ("req", "count", "env_count", "num_lo", "num_hi",
                         "azone", "acap", "schedulable", "base_req"):
                assert np.array_equal(getattr(a, name), getattr(b, name)), name
            for d in range(len(a.allowed)):
                assert np.array_equal(a.allowed[d], b.allowed[d])
        assert len(cache) > 0  # the cache actually engaged

    def test_distinct_requirements_never_share_a_row(self, catalog_items):
        catalog = encode.encode_catalog(catalog_items)
        cache = {}
        a = Pod("a", requests=Resources({"cpu": "1", "memory": "1Gi"}),
                node_selector={wk.ARCH_LABEL: "arm64"})
        b = Pod("b", requests=Resources({"cpu": "1", "memory": "1Gi"}),
                node_selector={wk.ARCH_LABEL: "amd64"})
        classes = encode.group_pods([a, b])
        with_cache, without = self._encode_pair(classes, catalog, cache)
        for d in range(len(with_cache.allowed)):
            assert np.array_equal(with_cache.allowed[d], without.allowed[d])
        compat = encode.compat_matrix(catalog, with_cache)
        assert not np.array_equal(compat[0], compat[1])


class TestDeltaWire:
    def test_full_then_delta_then_identical_decisions(self, client, catalog_items):
        pool = NodePool("default")
        sd = TPUSolver(g_max=64, client=client, incremental=True)
        host = TPUSolver(g_max=64, incremental=False)
        rng = np.random.default_rng(5)
        pods = churn_pods(rng, 0, 50)
        assert decision_sig(sd.solve(pool, catalog_items, list(pods))) == decision_sig(
            host.solve(pool, catalog_items, list(pods)))
        assert client.last_delta["mode"] == "full"
        # small churn: a delta ship with few dirty rows, >=5x fewer bytes
        pods2 = pods[:-4] + churn_pods(rng, 1, 4)
        assert decision_sig(sd.solve(pool, catalog_items, list(pods2))) == decision_sig(
            host.solve(pool, catalog_items, list(pods2)))
        ld = client.last_delta
        assert ld["mode"] == "delta"
        assert 0 <= ld["rows"] <= 8
        assert ld["payload_bytes"] < ld["full_bytes"]

    def test_payload_reduction_at_realistic_class_count(self, client, catalog_items):
        """The >=5x wire-bytes claim needs a realistic class count (the
        tiny suites above pad to c_pad=8 where per-row framing dominates):
        ~60 distinct classes, one dirty."""
        pool = NodePool("default")
        sd = TPUSolver(g_max=64, client=client)

        def wave(tick, extra):
            pods = [
                Pod(f"w-{tick}-{i}",
                    requests=Resources({"cpu": f"{100 + 10 * (i % 60)}m", "memory": "512Mi"}))
                for i in range(120)
            ]
            pods += [
                Pod(f"surge-{tick}-{i}",
                    requests=Resources({"cpu": "3", "memory": "6Gi"}))
                for i in range(extra)
            ]
            return pods

        sd.solve(pool, catalog_items, wave(0, 2))
        sd.solve(pool, catalog_items, wave(1, 5))
        ld = client.last_delta
        assert ld["mode"] == "delta"
        assert ld["payload_bytes"] * 5 <= ld["full_bytes"]

    def test_seeded_churn_differential(self, client, catalog_items):
        """Property-style: seeded churn sequences through the delta wire
        vs the in-process host backend -- bit-identical every tick."""
        pool = NodePool("default")
        sd = TPUSolver(g_max=64, client=client, incremental=True)
        host = TPUSolver(g_max=64, incremental=False)
        for seed in (21, 22):
            rng = np.random.default_rng(seed)
            for tick in range(5):
                pods = churn_pods(rng, tick, int(rng.integers(25, 80)))
                remote = sd.solve(pool, catalog_items, list(pods))
                local = host.solve(pool, catalog_items, list(pods))
                assert decision_sig(remote) == decision_sig(local), (seed, tick)
        assert metrics.DELTA_SOLVES.value(mode="delta") > 0

    def test_delta_disabled_client_ships_full(self, server, catalog_items):
        c = SolverClient(server.address[0], server.address[1], delta=False)
        try:
            pool = NodePool("default")
            s = TPUSolver(g_max=64, client=c)
            for tick in range(2):
                s.solve(pool, catalog_items, churn_pods(np.random.default_rng(9), tick, 30))
            assert c.last_delta["mode"] == "bypass"
            assert c.last_delta["payload_bytes"] == c.last_delta["full_bytes"]
        finally:
            c.close()

    def test_epoch_loss_restages_transparently(self, server, client, catalog_items):
        pool = NodePool("default")
        sd = TPUSolver(g_max=64, client=client)
        host = TPUSolver(g_max=64)
        rng = np.random.default_rng(13)
        pods = churn_pods(rng, 0, 40)
        sd.solve(pool, catalog_items, list(pods))
        # the sidecar forgets every class epoch (restart analogue)
        with server._lock:
            server._epochs.clear()
        before = metrics.DELTA_EPOCH_RESTAGES.value()
        pods2 = pods[:-3] + churn_pods(rng, 1, 3)
        res = sd.solve(pool, catalog_items, list(pods2))
        assert decision_sig(res) == decision_sig(host.solve(pool, catalog_items, list(pods2)))
        assert metrics.DELTA_EPOCH_RESTAGES.value() == before + 1
        assert client.last_delta["mode"] == "full"  # the retry re-established

    def test_pipelined_stale_epoch_surfaces_then_recovers(self, server, client, catalog_items):
        solver = TPUSolver(g_max=64, client=client)
        entry = solver._catalog(catalog_items)
        rng = np.random.default_rng(17)
        classes = encode.group_pods(churn_pods(rng, 0, 30))
        cs = encode.encode_classes(classes, entry.tensors, c_pad=32)
        # establish the epoch, then alter one row so the next ship is a delta
        h = client.begin_solve_compact(entry.seqnum, entry.tensors, cs, g_max=64)
        client.finish_solve_compact(h)
        assert client.last_delta["mode"] == "full"
        cs2 = encode.encode_classes(classes, entry.tensors, c_pad=32)
        cs2.count[0] += 1
        with server._lock:
            server._epochs.clear()
        h2 = client.begin_solve_compact(entry.seqnum, entry.tensors, cs2, g_max=64)
        assert client.last_delta["mode"] == "delta"
        with pytest.raises(StaleEpochError):
            client.finish_solve_compact(h2)
        # the synchronous retry full-restages (the service ladder's rung)
        dec = client.solve_classes_compact(entry.seqnum, entry.tensors, cs2, g_max=64)
        assert int(dec.n_open) >= 0
        assert client.last_delta["mode"] == "full"

    def test_staged_catalog_eviction_counted(self, server, client, catalog_items):
        catalog = encode.encode_catalog(catalog_items[:8])
        before = metrics.SOLVER_STAGED_EVICTIONS.value(kind="catalog")
        for i in range(6):
            client.stage_catalog(f"evict-{i}", catalog)
        assert metrics.SOLVER_STAGED_EVICTIONS.value(kind="catalog") > before
        info = client.debug_info()
        assert "evict-5" in info["staged_seqnums"]
        assert info["evictions"]["catalog"] >= 1
        # solving against an evicted seqnum restages transparently
        classes = encode.group_pods(churn_pods(np.random.default_rng(3), 0, 10))
        cs = encode.encode_classes(classes, catalog, c_pad=16)
        dec = client.solve_classes_compact("evict-0", catalog, cs, g_max=32)
        assert int(dec.n_open) >= 0

    def test_class_epoch_eviction_counted(self, server, client, catalog_items):
        """More than 4 live epoch chains force class-epoch evictions; the
        evicted chain's next delta restages transparently."""
        pool = NodePool("default")
        solvers = [
            (TPUSolver(g_max=64, client=client), None)
        ]
        # 5 distinct catalogs = 5 seqnums = 5 epoch chains on the server
        before = metrics.SOLVER_STAGED_EVICTIONS.value(kind="class_epoch")
        s = solvers[0][0]
        for i in range(5):
            items = catalog_items[i : i + 20]
            s.solve(pool, items, churn_pods(np.random.default_rng(i), i, 10))
        assert metrics.SOLVER_STAGED_EVICTIONS.value(kind="class_epoch") > before


class TestDescribeWire:
    def test_document_shape(self, client, catalog_items):
        pool = NodePool("default")
        s = TPUSolver(g_max=64, client=client)
        s.solve(pool, catalog_items, churn_pods(np.random.default_rng(1), 0, 20))
        doc = s.describe_wire()
        assert doc["wire"] is True and doc["delta_enabled"] is True
        assert "last_delta" in doc and "group_stats" in doc
        assert "server" in doc and "evictions" in doc["server"]

    def test_host_only_document(self):
        s = TPUSolver(g_max=32)
        doc = s.describe_wire()
        assert doc["wire"] is False


class TestCorpusDeltaReplay:
    def test_delta_backend_matches_golden_digest(self):
        """The committed sim corpus through the delta path: decision
        digests must equal the golden host digests bit-for-bit."""
        from karpenter_tpu.sim.replay import replay
        from karpenter_tpu.sim.trace import read_trace

        with open(os.path.join(GOLDEN_DIR, "digests.json")) as f:
            golden = json.load(f)
        events = read_trace(os.path.join(GOLDEN_DIR, "diurnal-small.jsonl"))
        seed = next(e["seed"] for e in events if e.get("ev") == "header")
        res = replay(events, backend="delta", seed=seed)
        assert res.digest == golden["diurnal-small"]
