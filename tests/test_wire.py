"""Wire transport v2: zero-copy framing, the shared-memory ring, trimmed
replies (reply_v2), and the transport differential.

The acceptance contracts this module pins (ISSUE 7):

- encode/decode payload-copy counters read ZERO on the warm delta path
  (zero-copy framing end to end, including the epoch store's
  copy-on-first-write discipline -- the old rpc.py defensive copy);
- reply_v2 ships only decision rows: >= 3x fewer reply bytes than the v1
  dense shape at a realistic tier, decisions bit-identical;
- shm, TCP, and in-process host paths produce identical decisions across
  sync, pipelined, delta, and breaker-recovery ladders, and the sim
  corpus digest matches the committed golden through the tcp backend;
- corrupt/attach-failure shm failpoints degrade cleanly to the socket
  transport (then the breaker), never a wrong decision.
"""
import json
import os
import socket as socket_mod
import threading
import time

import numpy as np
import pytest

from karpenter_tpu import metrics
from karpenter_tpu.apis import NodePool, Pod, TPUNodeClass
from karpenter_tpu.scheduling import Resources
from karpenter_tpu.solver import encode, ffd, shm
from karpenter_tpu.solver.rpc import (
    SHM_MAX_FAILURES, SolverClient, SolverServer, _recv_frame, _send_frame,
    expand_reply_v2,
)
from karpenter_tpu.solver.service import TPUSolver

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "golden", "scenarios")


@pytest.fixture(scope="module")
def catalog_items():
    from karpenter_tpu.apis.nodeclass import SubnetStatus
    from karpenter_tpu.cache.unavailable_offerings import UnavailableOfferings
    from karpenter_tpu.kwok.cloud import FakeCloud
    from karpenter_tpu.providers.instancetype import gen_catalog
    from karpenter_tpu.providers.instancetype.offerings import OfferingsBuilder
    from karpenter_tpu.providers.instancetype.provider import InstanceTypeProvider
    from karpenter_tpu.providers.instancetype.types import Resolver
    from karpenter_tpu.providers.pricing import PricingProvider

    cloud = FakeCloud()
    prov = InstanceTypeProvider(
        cloud,
        Resolver(gen_catalog.REGION),
        OfferingsBuilder(
            PricingProvider(cloud, cloud, gen_catalog.REGION),
            UnavailableOfferings(),
            {z.name: z.zone_id for z in cloud.describe_zones()},
        ),
        UnavailableOfferings(),
    )
    nc = TPUNodeClass("default")
    nc.status_subnets = [
        SubnetStatus(s.id, s.zone, s.zone_id) for s in cloud.describe_subnets()
    ]
    return prov.list(nc)


def make_pods(n, cpu="500m", mem="1Gi", prefix="p"):
    return [
        Pod(f"{prefix}{i}", requests=Resources({"cpu": cpu, "memory": mem}))
        for i in range(n)
    ]


def _sig(result):
    return (
        sorted(
            (g.instance_types[0].name, tuple(sorted(p.metadata.name for p in g.pods)))
            for g in result.new_groups
        ),
        sorted(result.unschedulable),
        sorted(result.existing_assignments.items()),
    )


def _copies(side):
    return metrics.WIRE_PAYLOAD_COPIES.value(side=side)


# -- the ring itself ----------------------------------------------------------


class TestRing:
    def test_roundtrip_both_directions(self, tmp_path):
        seg = shm.ShmSegment.create(size=65536, directory=str(tmp_path))
        try:
            att = shm.ShmSegment.attach(seg.path, 65536)
            c = att.endpoint("client", timeout=2.0)
            s = seg.endpoint("server", timeout=2.0)
            c.sendall(b"hello-from-client")
            buf = bytearray(17)
            got = 0
            while got < 17:
                got += s.recv_into(memoryview(buf)[got:])
            assert bytes(buf) == b"hello-from-client"
            s.sendmsg([b"reply-", memoryview(np.arange(4, dtype=np.uint8))])
            buf2 = bytearray(10)
            got = 0
            while got < 10:
                got += c.recv_into(memoryview(buf2)[got:])
            assert bytes(buf2) == b"reply-\x00\x01\x02\x03"
            att.close()
        finally:
            seg.destroy()

    def test_wraparound_preserves_bytes(self, tmp_path):
        """Frames larger than the remaining tail of the ring split across
        the wrap; the reader reassembles them byte-exact."""
        seg = shm.ShmSegment.create(size=4096, directory=str(tmp_path))
        try:
            tx = seg.endpoint("client", timeout=2.0)
            rx = seg.endpoint("server", timeout=2.0)
            rng = np.random.default_rng(7)
            for i in range(20):
                payload = rng.integers(0, 256, size=3000, dtype=np.uint8).tobytes()
                tx.sendall(payload)
                buf = bytearray(3000)
                got = 0
                while got < 3000:
                    got += rx.recv_into(memoryview(buf)[got:])
                assert bytes(buf) == payload, f"iteration {i} corrupted"
        finally:
            seg.destroy()

    def test_ring_full_backpressure_counted(self, tmp_path):
        """A frame bigger than the ring blocks until the reader drains --
        flow control exactly like a full socket buffer -- and the stall
        is counted into karpenter_wire_shm_ring_full_total."""
        seg = shm.ShmSegment.create(size=4096, directory=str(tmp_path))
        try:
            tx = seg.endpoint("client", timeout=10.0)
            rx = seg.endpoint("server", timeout=10.0)
            payload = bytes(range(256)) * 40  # 10240 bytes > 4096 ring
            before = metrics.WIRE_SHM_RING_FULL.value()
            received = bytearray()

            def reader():
                while len(received) < len(payload):
                    buf = bytearray(2048)
                    n = rx.recv_into(memoryview(buf))
                    received.extend(buf[:n])

            t = threading.Thread(target=reader, daemon=True)
            t.start()
            tx.sendall(payload)
            t.join(timeout=10)
            assert bytes(received) == payload
            assert metrics.WIRE_SHM_RING_FULL.value() > before
        finally:
            seg.destroy()

    def test_recv_timeout_raises_oserror(self, tmp_path):
        seg = shm.ShmSegment.create(size=4096, directory=str(tmp_path))
        try:
            rx = seg.endpoint("server", timeout=0.05)
            with pytest.raises(OSError):  # socket.timeout subclasses OSError
                rx.recv_into(memoryview(bytearray(4)))
        finally:
            seg.destroy()

    def test_peer_close_raises_connection_error(self, tmp_path):
        seg = shm.ShmSegment.create(size=4096, directory=str(tmp_path))
        try:
            c = seg.endpoint("client", timeout=5.0)
            s = seg.endpoint("server", timeout=5.0)
            c.close()
            with pytest.raises(ConnectionError):
                s.recv_into(memoryview(bytearray(4)))
        finally:
            seg.destroy()

    def test_attach_validates_geometry_and_magic(self, tmp_path):
        seg = shm.ShmSegment.create(size=4096, directory=str(tmp_path))
        try:
            with pytest.raises(shm.ShmAttachError):
                shm.ShmSegment.attach(seg.path, 8192)  # wrong size
            with pytest.raises(shm.ShmAttachError):
                shm.ShmSegment.attach(str(tmp_path / "nope"), 4096)
            seg.mv[0:8] = b"GARBAGE!"
            with pytest.raises(shm.ShmAttachError):
                shm.ShmSegment.attach(seg.path, 4096)
        finally:
            seg.destroy()

    def test_cleanup_stale_sweeps_dead_pid_segments(self, tmp_path):
        d = str(tmp_path)
        # a plausibly-dead pid (max pid is far below this on test rigs)
        dead = os.path.join(d, f"{shm.PREFIX}999999999-deadbeef")
        open(dead, "wb").close()
        live = os.path.join(d, f"{shm.PREFIX}{os.getpid()}-cafecafe")
        open(live, "wb").close()
        unrelated = os.path.join(d, "not-a-ring-file")
        open(unrelated, "wb").close()
        removed = shm.cleanup_stale(d)
        assert removed == 1
        assert not os.path.exists(dead)
        assert os.path.exists(live) and os.path.exists(unrelated)

    def test_server_start_sweeps_stale_segments_even_with_shm_off(self, tmp_path):
        """The post-incident move -- restart the sidecar with the shm kill
        switch set -- must still unlink crash leftovers: the janitor runs
        at every server start, shm enabled or not."""
        d = str(tmp_path / "rings")
        os.makedirs(d)
        dead = os.path.join(d, f"{shm.PREFIX}999999999-deadbeef")
        open(dead, "wb").close()
        srv = SolverServer(path=str(tmp_path / "solver.sock"),
                           shm=False, shm_dir=d).start()
        try:
            assert not os.path.exists(dead)
        finally:
            srv.stop()


# -- zero-copy framing --------------------------------------------------------


class TestZeroCopyFraming:
    def test_contiguous_tensors_ship_copy_free(self):
        s1, s2 = socket_mod.socketpair()
        try:
            a = np.arange(24, dtype=np.float32).reshape(4, 6)
            b = np.arange(5, dtype=np.int64)
            before = _copies("encode")
            _send_frame(s1, {"op": "x"}, [("a", a), ("b", b)])
            assert _copies("encode") == before, "contiguous send must not copy"
            header, tensors = _recv_frame(s2)
            np.testing.assert_array_equal(tensors["a"], a)
            np.testing.assert_array_equal(tensors["b"], b)
        finally:
            s1.close()
            s2.close()

    def test_noncontiguous_tensor_copy_is_counted(self):
        s1, s2 = socket_mod.socketpair()
        try:
            a = np.arange(24, dtype=np.float32).reshape(4, 6).T  # F-order view
            before = _copies("encode")
            _send_frame(s1, {"op": "x"}, [("a", a)])
            assert _copies("encode") == before + 1
            _, tensors = _recv_frame(s2)
            np.testing.assert_array_equal(tensors["a"], a)
        finally:
            s1.close()
            s2.close()

    def test_received_tensors_are_read_only_views(self):
        s1, s2 = socket_mod.socketpair()
        try:
            _send_frame(s1, {"op": "x"}, [("a", np.ones((3,), np.float32))])
            _, tensors = _recv_frame(s2)
            assert not tensors["a"].flags.writeable
            with pytest.raises(ValueError):
                tensors["a"][0] = 2.0
        finally:
            s1.close()
            s2.close()

    def test_unrelated_failpoint_keeps_zero_copy_path(self, failpoints):
        """An armed site elsewhere in the process (a crash drill, a
        latency drill on instance.launch) must not silently disable
        scatter-gather: only the frame's OWN corrupt site buys the
        joining copy."""
        failpoints.arm("instance.launch", "latency", "0")
        s1, s2 = socket_mod.socketpair()
        try:
            a = np.arange(24, dtype=np.float32).reshape(4, 6)
            before = _copies("encode")
            _send_frame(s1, {"op": "x"}, [("a", a)])
            assert _copies("encode") == before, (
                "unrelated armed site disabled the zero-copy send"
            )
            _, tensors = _recv_frame(s2)
            np.testing.assert_array_equal(tensors["a"], a)
        finally:
            s1.close()
            s2.close()

    def test_corruption_still_detected_by_crc(self, failpoints):
        """The chaos join path: with the corrupt site armed the frame is
        assembled, one byte flips, and the receiver's crc/JSON integrity
        checks surface it as ConnectionError -- unchanged under v2."""
        failpoints.arm("rpc.frame.corrupt", "corrupt", times=1)
        s1, s2 = socket_mod.socketpair()
        try:
            _send_frame(s1, {"op": "x"}, [("a", np.arange(1000, dtype=np.float32))])
            with pytest.raises(ConnectionError):
                _recv_frame(s2)
        finally:
            s1.close()
            s2.close()

    def test_exhausted_corrupt_discipline_restores_zero_copy(self, failpoints):
        """Once a bounded corrupt drill has fully fired, later frames go
        back to scatter-gather: a spent discipline must not keep taxing
        every frame with the joining copy for the life of the process."""
        failpoints.arm("rpc.frame.corrupt", "corrupt", times=1)
        a = np.arange(1000, dtype=np.float32)
        s1, s2 = socket_mod.socketpair()
        try:
            _send_frame(s1, {"op": "x"}, [("a", a)])  # the one fire
        finally:
            s1.close()
            s2.close()
        assert failpoints.fires("rpc.frame.corrupt") == 1
        s1, s2 = socket_mod.socketpair()
        try:
            before = _copies("encode")
            for _ in range(3):
                _send_frame(s1, {"op": "x"}, [("a", a)])
                _, tensors = _recv_frame(s2)
                np.testing.assert_array_equal(tensors["a"], a)
            assert _copies("encode") == before, (
                "spent corrupt discipline kept the joining-copy path"
            )
        finally:
            s1.close()
            s2.close()


# -- shm negotiation + degrade ------------------------------------------------


class TestShmNegotiation:
    def test_unix_client_negotiates_ring(self, tmp_path, catalog_items):
        path = str(tmp_path / "solver.sock")
        srv = SolverServer(path=path).start()
        client = SolverClient(path=path)
        try:
            assert "shm" in client.features()
            assert client._ring is not None, "UNIX client should be on the ring"
            solver = TPUSolver(g_max=64, client=client)
            res = solver.solve(NodePool("default"), catalog_items, make_pods(8))
            assert not res.unschedulable
            assert solver.describe_wire()["transport"] == "shm"
            assert metrics.WIRE_TRANSPORT.value(transport="shm") == 1.0
        finally:
            client.close()
            srv.stop()

    def test_env_kill_switch(self, tmp_path, monkeypatch):
        monkeypatch.setenv("KARPENTER_TPU_SHM", "0")
        path = str(tmp_path / "solver.sock")
        srv = SolverServer(path=path).start()
        client = SolverClient(path=path)
        try:
            assert client.ping() is True
            assert client._ring is None
        finally:
            client.close()
            srv.stop()

    def test_server_without_shm_keeps_socket(self, tmp_path):
        path = str(tmp_path / "solver.sock")
        srv = SolverServer(path=path, shm=False).start()
        client = SolverClient(path=path)
        try:
            assert "shm" not in client.features()
            assert client.ping() is True
            assert client._ring is None
        finally:
            client.close()
            srv.stop()

    def test_attach_failpoint_degrades_to_socket(self, tmp_path, catalog_items,
                                                 failpoints):
        """rpc.shm.attach fires -> the connection stays on the socket with
        the stream intact; decisions are unaffected."""
        failpoints.arm("rpc.shm.attach", "error", "ConnectionError")
        path = str(tmp_path / "solver.sock")
        srv = SolverServer(path=path).start()
        client = SolverClient(path=path)
        try:
            solver = TPUSolver(g_max=64, client=client)
            res = solver.solve(NodePool("default"), catalog_items, make_pods(9))
            assert client._ring is None
            assert failpoints.fires("rpc.shm.attach") >= 1
            want = TPUSolver(g_max=64).solve(
                NodePool("default"), catalog_items, make_pods(9))
            assert _sig(res) == _sig(want)
        finally:
            client.close()
            srv.stop()

    def test_attach_failpoint_inside_attach_degrades_to_socket(
            self, tmp_path, catalog_items, failpoints):
        """The rpc.shm.attach site evals twice per negotiation (top of
        _try_shm, then inside ShmSegment.attach): a discipline whose
        FIRST fire lands on the inner eval must still leave the handshake
        on the socket, never tear down the whole connection."""
        failpoints.arm("rpc.shm.attach", "error", "ConnectionError", after=1)
        path = str(tmp_path / "solver.sock")
        srv = SolverServer(path=path).start()
        client = SolverClient(path=path)
        try:
            solver = TPUSolver(g_max=64, client=client)
            res = solver.solve(NodePool("default"), catalog_items, make_pods(9))
            assert client._ring is None
            assert failpoints.fires("rpc.shm.attach") >= 1
            want = TPUSolver(g_max=64).solve(
                NodePool("default"), catalog_items, make_pods(9))
            assert _sig(res) == _sig(want)
        finally:
            client.close()
            srv.stop()

    def test_close_zeroes_both_transport_gauges(self, tmp_path):
        """A closed client reports NO active transport: close() must zero
        the tcp series too, or a socket-mode client looks alive forever."""
        path = str(tmp_path / "solver.sock")
        srv = SolverServer(path=path, shm=False).start()
        client = SolverClient(path=path)
        try:
            assert client.ping() is True
            assert metrics.WIRE_TRANSPORT.value(transport="tcp") == 1.0
        finally:
            client.close()
            srv.stop()
        assert metrics.WIRE_TRANSPORT.value(transport="tcp") == 0.0
        assert metrics.WIRE_TRANSPORT.value(transport="shm") == 0.0

    def test_sidecar_death_does_not_stick_to_tcp(self, tmp_path):
        """Peer death is not segment badness: a crash-looping sidecar gets
        a FRESH segment per reconnect, so repeated sidecar deaths must not
        permanently disable the ring -- only stream corruption counts
        toward SHM_MAX_FAILURES."""
        path = str(tmp_path / "solver.sock")
        client = SolverClient(path=path, connect_timeout=2.0)
        try:
            for _ in range(3):  # more deaths than SHM_MAX_FAILURES
                srv = SolverServer(path=path).start()
                assert client.ping() is True
                assert client._ring is not None
                srv.stop()
                with pytest.raises((ConnectionError, OSError)):
                    client.ping()  # peer gone: fails, must not count
            srv = SolverServer(path=path).start()
            try:
                assert client.ping() is True
                assert client._ring is not None, "sidecar deaths made tcp sticky"
                assert client._shm_failures == 0
            finally:
                srv.stop()
        finally:
            client.close()

    def test_throwaway_client_does_not_clobber_transport_gauge(self, tmp_path):
        """The gauge is process-global and belongs to the PRIMARY client:
        a track_transport=False connection (the breaker's half-open probe)
        must neither set it on connect nor zero it on close."""
        path = str(tmp_path / "solver.sock")
        srv = SolverServer(path=path, shm=False).start()
        main = SolverClient(path=path)
        try:
            assert main.ping() is True
            assert metrics.WIRE_TRANSPORT.value(transport="tcp") == 1.0
            probe = SolverClient(path=path, track_transport=False)
            try:
                assert probe.ping() is True
            finally:
                probe.close()
            assert metrics.WIRE_TRANSPORT.value(transport="tcp") == 1.0, (
                "throwaway client clobbered the transport gauge"
            )
        finally:
            main.close()
            srv.stop()

    def test_corrupt_shm_degrades_to_tcp_never_wrong(self, tmp_path,
                                                     catalog_items, failpoints):
        """The degrade ladder of the acceptance criteria: an unboundedly
        corrupting ring is DETECTED (crc -> ConnectionError), the solve
        falls back to the bit-identical host path (breaker accounting),
        and after SHM_MAX_FAILURES the client stays on the socket -- where
        solves flow over the wire again. No decision is ever wrong."""
        from karpenter_tpu.solver.breaker import CircuitBreaker

        failpoints.arm("rpc.shm.corrupt", "corrupt")
        path = str(tmp_path / "solver.sock")
        srv = SolverServer(path=path).start()
        client = SolverClient(path=path, timeout=10.0, connect_timeout=0.5)
        breaker = CircuitBreaker(failure_threshold=3, backoff_base=1000.0)
        solver = TPUSolver(g_max=64, client=client, breaker=breaker)
        ref = TPUSolver(g_max=64)
        pool = NodePool("default")
        try:
            for i in range(SHM_MAX_FAILURES + 2):
                pods = make_pods(6 + i, prefix=f"c{i}-")
                got = solver.solve(pool, catalog_items, list(pods))
                want = ref.solve(pool, catalog_items, list(pods))
                assert _sig(got) == _sig(want), f"solve {i} diverged"
            assert failpoints.fires("rpc.shm.corrupt") >= 1
            assert client._shm_failures >= SHM_MAX_FAILURES
            # the degrade is sticky: the live connection is on the socket
            # and solves flow over the WIRE again (not the host fallback)
            assert client._ring is None
            assert client.ping() is True
            if breaker.state != "closed":
                assert breaker.probe_now() is True
        finally:
            breaker.stop()
            client.close()
            srv.stop()

    def test_segments_are_cleaned_up(self, tmp_path, catalog_items):
        shm_dir = str(tmp_path / "segs")
        path = str(tmp_path / "solver.sock")
        srv = SolverServer(path=path, shm_dir=shm_dir).start()
        client = SolverClient(path=path)
        try:
            assert client.ping() is True
            assert client._ring is not None
            assert len(os.listdir(shm_dir)) == 1
            client.close()
            deadline = time.time() + 5
            while os.listdir(shm_dir) and time.time() < deadline:
                time.sleep(0.02)
            assert not os.listdir(shm_dir), "segment not unlinked on teardown"
        finally:
            client.close()
            srv.stop()


# -- reply_v2 -----------------------------------------------------------------


class TestReplyV2:
    @staticmethod
    def _encoded(catalog_items, pods, g_max=256):
        pool = NodePool("default")
        catalog = encode.encode_catalog(catalog_items)
        classes = encode.group_pods(pods, extra_requirements=pool.requirements())
        cs = encode.encode_classes(
            classes, catalog, c_pad=encode.bucket(len(classes), 16))
        return catalog, cs

    def test_v2_matches_v1_bit_for_bit_in_decisions(self, tmp_path, catalog_items):
        path = str(tmp_path / "solver.sock")
        srv = SolverServer(path=path).start()
        c2 = SolverClient(path=path, shm=False)
        c1 = SolverClient(path=path, shm=False, reply_v2=False)
        try:
            pods = make_pods(60) + make_pods(20, cpu="2", mem="4Gi", prefix="big")
            catalog, cs = self._encoded(catalog_items, pods)
            dec2 = c2.solve_classes_compact("v2-seq", catalog, cs, g_max=256)
            dec1 = c1.solve_classes_compact("v2-seq", catalog, cs, g_max=256)
            assert c2.last_reply["v"] == 2 and c1.last_reply["v"] == 1
            e2 = ffd.expand_compact(dec2, cs.c_pad, 256, catalog.k_pad,
                                    encode.Z_PAD, encode.CT)
            e1 = ffd.expand_compact(dec1, cs.c_pad, 256, catalog.k_pad,
                                    encode.Z_PAD, encode.CT)
            assert e1 is not None and e2 is not None
            take2, unplaced2, n_open2, gmask2, gzone2, gcap2 = e2
            take1, unplaced1, n_open1, gmask1, gzone1, gcap1 = e1
            assert n_open1 == n_open2
            np.testing.assert_array_equal(take1, take2)
            np.testing.assert_array_equal(unplaced1, unplaced2)
            # decision-bearing rows (decode reads only [:n_open])
            np.testing.assert_array_equal(gmask1[:n_open1], gmask2[:n_open2])
            np.testing.assert_array_equal(gzone1[:n_open1], gzone2[:n_open2])
            np.testing.assert_array_equal(gcap1[:n_open1], gcap2[:n_open2])
        finally:
            c1.close()
            c2.close()
            srv.stop()

    def test_reply_bytes_reduced_3x(self, tmp_path, catalog_items):
        """The acceptance bar: >= 3x fewer reply bytes than the dense v1
        shape at a realistic class-count/group-budget tier."""
        path = str(tmp_path / "solver.sock")
        srv = SolverServer(path=path).start()
        c2 = SolverClient(path=path, shm=False)
        c1 = SolverClient(path=path, shm=False, reply_v2=False)
        try:
            pods = make_pods(400) + make_pods(100, cpu="1", mem="2Gi", prefix="m")
            catalog, cs = self._encoded(catalog_items, pods)
            c2.solve_classes_compact("rb-seq", catalog, cs, g_max=512)
            c1.solve_classes_compact("rb-seq", catalog, cs, g_max=512)
            v2, v1 = c2.last_reply["bytes"], c1.last_reply["bytes"]
            assert v2 > 0 and v1 / v2 >= 3.0, (v1, v2)
        finally:
            c1.close()
            c2.close()
            srv.stop()

    def test_overflow_reply_maps_to_dense_refetch(self):
        """An overflow v2 reply reconstructs with an empty idx, which
        expand_compact maps to None -- the existing dense-refetch rung."""
        dec = expand_reply_v2({"nnz": 999, "n_open": 4}, {}, g_max=8)
        assert ffd.expand_compact(dec, 4, 8, 64, encode.Z_PAD, encode.CT) is None

    def test_solver_ladder_handles_overflow_end_to_end(self, tmp_path,
                                                       catalog_items, monkeypatch):
        """Force the sparse budget to overflow: the wire ladder must land
        on the dense op and still produce the correct decision."""
        from karpenter_tpu.solver import rpc as rpc_mod

        path = str(tmp_path / "solver.sock")
        srv = SolverServer(path=path).start()
        client = SolverClient(path=path, shm=False, delta=False)
        try:
            pods = make_pods(40) + make_pods(10, cpu="2", mem="4Gi", prefix="b")
            want = TPUSolver(g_max=64).solve(
                NodePool("default"), catalog_items, list(pods))
            # a pathological nnz budget: every compact solve overflows
            monkeypatch.setattr(rpc_mod.ffd, "nnz_budget", lambda c, g: 1)
            solver = TPUSolver(g_max=64, client=client)
            got = solver.solve(NodePool("default"), catalog_items, list(pods))
            assert _sig(got) == _sig(want)
        finally:
            client.close()
            srv.stop()


# -- the epoch store's read-only discipline (satellite 1) ---------------------


class TestEpochReadOnly:
    def test_full_ship_stores_views_and_warm_path_copies_nothing(
            self, tmp_path, catalog_items):
        """Regression for the rpc.py:444 defensive copy: a full ship's
        epoch holds the received READ-ONLY frame views (no writable copy);
        the first delta pays one counted copy-on-write per tensor; every
        warm tick after that patches in place -- encode AND decode copy
        counters stay flat, the zero-copy acceptance criterion."""
        path = str(tmp_path / "solver.sock")
        srv = SolverServer(path=path).start()
        client = SolverClient(path=path, shm=False)  # same-process server: one registry
        solver = TPUSolver(g_max=64, client=client, incremental=True)
        pool = NodePool("default")

        def wave(i):
            return (
                make_pods(20, prefix=f"w{i}-")
                + make_pods(4 + i % 3, cpu="2", mem="4Gi", prefix=f"s{i}-")
            )

        try:
            from karpenter_tpu.solver.oracle import Scheduler

            def sched():
                zones = {
                    o.zone for it in catalog_items for o in it.available_offerings()
                }
                return Scheduler(
                    nodepools=[pool],
                    instance_types={pool.name: catalog_items}, zones=zones,
                )

            solver.schedule(sched(), wave(0))  # full ship establishes the epoch
            assert client.last_delta["mode"] == "full"
            with srv._lock:
                assert srv._epochs, "epoch not established"
                for ep in srv._epochs.values():
                    for name, arr in ep.items():
                        assert not arr.flags.writeable, (
                            f"epoch tensor {name} was defensively copied"
                        )
            solver.schedule(sched(), wave(1))  # first delta: counted CoW
            assert client.last_delta["mode"] == "delta"
            enc0, dec0 = _copies("encode"), _copies("decode")
            for i in range(2, 5):  # warm steady state: ZERO copies
                solver.schedule(sched(), wave(i))
                assert client.last_delta["mode"] == "delta"
            assert _copies("encode") == enc0, "warm delta path copied on encode"
            assert _copies("decode") == dec0, "warm delta path copied on decode"
        finally:
            client.close()
            srv.stop()


# -- transport differential ---------------------------------------------------


class TestTransportDifferential:
    def _rig(self, tmp_path, **client_kw):
        path = str(tmp_path / "solver.sock")
        srv = SolverServer(path=path).start()
        client = SolverClient(path=path, timeout=10.0, connect_timeout=0.5,
                              **client_kw)
        return srv, client

    def test_host_tcp_shm_identical_sync_and_pipelined(self, tmp_path,
                                                       catalog_items):
        pool = NodePool("default")
        srv, c_shm = self._rig(tmp_path)
        c_tcp = SolverClient(path=srv.path, shm=False)
        try:
            s_host = TPUSolver(g_max=64)
            s_shm = TPUSolver(g_max=64, client=c_shm)
            s_tcp = TPUSolver(g_max=64, client=c_tcp)
            assert c_shm.features() and c_shm._ring is not None
            assert c_tcp.ping() and c_tcp._ring is None
            for i in range(3):
                pods = make_pods(10 + 7 * i, prefix=f"d{i}-")
                sig_host = _sig(s_host.solve(pool, catalog_items, list(pods)))
                assert sig_host == _sig(s_shm.solve(pool, catalog_items, list(pods)))
                assert sig_host == _sig(s_tcp.solve(pool, catalog_items, list(pods)))
                # pipelined halves through both transports
                p1 = s_shm.solve_begin(pool, catalog_items, list(pods))
                p2 = s_tcp.solve_begin(pool, catalog_items, list(pods))
                assert sig_host == _sig(s_shm.solve_finish(p1))
                assert sig_host == _sig(s_tcp.solve_finish(p2))
        finally:
            c_shm.close()
            c_tcp.close()
            srv.stop()

    def test_breaker_recovery_ladder_over_shm(self, tmp_path, catalog_items,
                                              failpoints):
        """Trip the breaker while on the ring, solve on the host fallback
        (same decision), re-promote through the probe, and resume on a
        freshly negotiated ring -- still the same decision."""
        from karpenter_tpu.solver.breaker import CLOSED, CircuitBreaker

        pool = NodePool("default")
        srv, client = self._rig(tmp_path)
        breaker = CircuitBreaker(failure_threshold=1, backoff_base=1000.0)
        solver = TPUSolver(g_max=64, client=client, breaker=breaker)
        ref = TPUSolver(g_max=64)
        try:
            pods = make_pods(12)
            assert _sig(solver.solve(pool, catalog_items, list(pods))) == _sig(
                ref.solve(pool, catalog_items, list(pods)))
            assert client._ring is not None
            # sever: refuse reconnects, kill the live connection
            failpoints.arm("rpc.client.connect", "error", "ConnectionError")
            client.close()
            got = solver.solve(pool, catalog_items, list(pods))
            assert _sig(got) == _sig(ref.solve(pool, catalog_items, list(pods)))
            assert breaker.state != CLOSED
            failpoints.reset()
            assert breaker.probe_now() is True and breaker.state == CLOSED
            got = solver.solve(pool, catalog_items, list(pods))
            assert _sig(got) == _sig(ref.solve(pool, catalog_items, list(pods)))
            assert client._ring is not None, "ring not renegotiated after recovery"
        finally:
            breaker.stop()
            client.close()
            srv.stop()

    def test_delta_chain_identical_across_transports(self, tmp_path,
                                                     catalog_items):
        """Warm delta ticks (epoch chain + reply_v2) through shm and tcp
        against the host path: identical decisions every tick."""
        from karpenter_tpu.solver.oracle import Scheduler

        pool = NodePool("default")
        zones = {o.zone for it in catalog_items for o in it.available_offerings()}

        def sched():
            return Scheduler(nodepools=[pool],
                             instance_types={pool.name: catalog_items}, zones=zones)

        def wave(i):
            return (
                make_pods(18, prefix=f"w{i}-")
                + make_pods(3 + i % 4, cpu="2", mem="4Gi", prefix=f"s{i}-")
            )

        srv, c_shm = self._rig(tmp_path)
        c_tcp = SolverClient(path=srv.path, shm=False)
        try:
            s_host = TPUSolver(g_max=64, incremental=False)
            s_shm = TPUSolver(g_max=64, client=c_shm, incremental=True)
            s_tcp = TPUSolver(g_max=64, client=c_tcp, incremental=True)
            for i in range(5):
                w = wave(i)
                sig_host = _sig(s_host.schedule(sched(), list(w)))
                assert sig_host == _sig(s_shm.schedule(sched(), list(w))), f"tick {i} shm"
                assert sig_host == _sig(s_tcp.schedule(sched(), list(w))), f"tick {i} tcp"
            assert c_shm.last_delta["mode"] == "delta"
            assert c_tcp.last_delta["mode"] == "delta"
        finally:
            c_shm.close()
            c_tcp.close()
            srv.stop()

    def test_corpus_digest_through_tcp_backend(self, tmp_path):
        """Sim corpus replay (acceptance): the committed diurnal-small
        golden digest holds through the tcp-pinned wire backend -- with
        the wire/pipelined/delta backends already on the shm ring by
        default (tests/test_sim.py), this closes shm == tcp == host."""
        from karpenter_tpu.sim.replay import replay
        from karpenter_tpu.sim.trace import read_trace

        events = read_trace(os.path.join(GOLDEN_DIR, "diurnal-small.jsonl"))
        with open(os.path.join(GOLDEN_DIR, "digests.json")) as f:
            golden = json.load(f)
        seed = events[0]["seed"]
        res = replay(events, backend="tcp", seed=seed, tmpdir=str(tmp_path))
        assert res.digest == golden["diurnal-small"]
