"""Pod (anti-)affinity enforcement in the scheduling oracle.

VERDICT round 2, item 5: required positive affinity must co-locate, a
violating placement must be rejected, and anti-affinity must be SYMMETRIC
(a resident pod's anti-affinity repels newcomers that match its selector).
Reference behavior: the core scheduling algebra (SURVEY.md section 2.3);
routing carves affinity-carrying classes to this oracle as the SUFFIX of
the canonical pass (round 5, solver/service.py TPUSolver._oracle_suffix)
or, when the partitions could couple, sends the whole batch here
(TPUSolver.supports / _aff_partition_blocked;
solver/consolidate.device_eligible for disruption verdicts).
"""
import pytest

from karpenter_tpu.apis import NodePool, Pod, labels as wk
from karpenter_tpu.apis.pod import PodAffinityTerm
from karpenter_tpu.scheduling import Resources
from karpenter_tpu.solver.oracle import ExistingNode, Scheduler


@pytest.fixture(scope="module")
def catalog_items():
    from karpenter_tpu.apis.nodeclass import SubnetStatus
    from karpenter_tpu.apis import TPUNodeClass
    from karpenter_tpu.cache.unavailable_offerings import UnavailableOfferings
    from karpenter_tpu.kwok.cloud import FakeCloud
    from karpenter_tpu.providers.instancetype import gen_catalog
    from karpenter_tpu.providers.instancetype.offerings import OfferingsBuilder
    from karpenter_tpu.providers.instancetype.provider import InstanceTypeProvider
    from karpenter_tpu.providers.instancetype.types import Resolver
    from karpenter_tpu.providers.pricing import PricingProvider

    cloud = FakeCloud()
    prov = InstanceTypeProvider(
        cloud,
        Resolver(gen_catalog.REGION),
        OfferingsBuilder(
            PricingProvider(cloud, cloud, gen_catalog.REGION),
            UnavailableOfferings(),
            {z.name: z.zone_id for z in cloud.describe_zones()},
        ),
        UnavailableOfferings(),
    )
    nc = TPUNodeClass("default")
    nc.status_subnets = [SubnetStatus(s.id, s.zone, s.zone_id) for s in cloud.describe_subnets()]
    return prov.list(nc)


def mk_sched(items, existing=(), pods_by_node=None, zones=None):
    pool = NodePool("default")
    all_zones = zones if zones is not None else {
        o.zone for it in items for o in it.available_offerings()
    }
    return pool, Scheduler(
        nodepools=[pool],
        instance_types={"default": items},
        existing_nodes=existing,
        pods_by_node=pods_by_node,
        zones=all_zones,
    )


def small(name, **kw):
    return Pod(name, requests=Resources({"cpu": "500m", "memory": "1Gi"}), **kw)


def affinity(selector, key=wk.HOSTNAME_LABEL, anti=False):
    return [PodAffinityTerm(label_selector=selector, topology_key=key, anti=anti)]


class TestPositiveAffinity:
    def test_required_affinity_colocates(self, catalog_items):
        """A follower pod with required affinity to app=web lands in the
        SAME group as the web pod."""
        web = small("web", labels={"app": "web"})
        follower = small("follower", affinity_terms=affinity({"app": "web"}))
        _, sched = mk_sched(catalog_items)
        result = sched.schedule([web, follower])
        assert not result.unschedulable
        group_of = {}
        for gi, g in enumerate(result.new_groups):
            for p in g.pods:
                group_of[p.metadata.name] = gi
        assert group_of["follower"] == group_of["web"]

    def test_bootstrap_rule_self_match(self, catalog_items):
        """First pod of a self-affine group may open a fresh node (k8s
        bootstrap rule); replicas then pile onto the same domain."""
        pods = [
            small(f"p{i}", labels={"app": "ring"}, affinity_terms=affinity({"app": "ring"}))
            for i in range(3)
        ]
        _, sched = mk_sched(catalog_items)
        result = sched.schedule(pods)
        assert not result.unschedulable
        assert len(result.new_groups) == 1

    def test_affinity_without_match_rejected(self, catalog_items):
        """Required affinity to a label no pod carries (and the pod itself
        does not carry) is unschedulable, not silently placed."""
        p = small("lonely", affinity_terms=affinity({"app": "db"}))
        _, sched = mk_sched(catalog_items)
        result = sched.schedule([p])
        assert "lonely" in result.unschedulable

    def test_affinity_to_full_node_rejected(self, catalog_items):
        """The matching pod sits on a FULL existing node: the follower may
        not open a fresh (empty) hostname domain -- it stays pending."""
        db = small("db", labels={"app": "db"})
        node = ExistingNode(
            name="n1",
            labels={wk.ZONE_LABEL: "us-central-1a"},
            allocatable=Resources({"cpu": "600m", "memory": "1100Mi", "pods": 8}),
        )
        node.used = Resources({"cpu": "600m", "memory": "1100Mi"})
        follower = small("follower", affinity_terms=affinity({"app": "db"}))
        _, sched = mk_sched(catalog_items, existing=[node], pods_by_node={"n1": [db]})
        result = sched.schedule([follower])
        assert "follower" in result.unschedulable

    def test_zone_affinity_follows_zone(self, catalog_items):
        """Zone-topology affinity: the follower's new group is pinned to the
        zone already hosting the matching pod."""
        web = small("web", labels={"app": "web"})
        node = ExistingNode(
            name="n1",
            labels={wk.ZONE_LABEL: "us-central-1b"},
            allocatable=Resources({"cpu": "600m", "memory": "1100Mi", "pods": 8}),
        )
        node.used = Resources({"cpu": "600m", "memory": "1100Mi"})  # full
        follower = small(
            "follower", affinity_terms=affinity({"app": "web"}, key=wk.ZONE_LABEL)
        )
        _, sched = mk_sched(catalog_items, existing=[node], pods_by_node={"n1": [web]})
        result = sched.schedule([follower])
        assert not result.unschedulable
        assert len(result.new_groups) == 1
        zreq = result.new_groups[0].requirements.get(wk.ZONE_LABEL)
        assert zreq is not None and set(zreq.values) == {"us-central-1b"}


class TestAntiAffinity:
    def test_self_anti_affinity_spreads(self, catalog_items):
        """Two replicas with hostname anti-affinity to their own label land
        on different groups."""
        pods = [
            small(
                f"r{i}", labels={"app": "spread"},
                affinity_terms=affinity({"app": "spread"}, anti=True),
            )
            for i in range(2)
        ]
        _, sched = mk_sched(catalog_items)
        result = sched.schedule(pods)
        assert not result.unschedulable
        assert len(result.new_groups) == 2

    def test_symmetric_anti_affinity_repels_newcomer(self, catalog_items):
        """A RESIDENT pod's anti-affinity term repels an incoming pod that
        matches its selector, even though the incoming pod carries no anti
        term itself (reference: full symmetry in the core scheduler)."""
        guard = small(
            "guard", labels={"app": "guard"},
            affinity_terms=affinity({"app": "web"}, anti=True),
        )
        node = ExistingNode(
            name="n1",
            labels={wk.ZONE_LABEL: "us-central-1a"},
            allocatable=Resources({"cpu": "8", "memory": "16Gi", "pods": 20}),
        )
        web = small("web", labels={"app": "web"})
        _, sched = mk_sched(catalog_items, existing=[node], pods_by_node={"n1": [guard]})
        result = sched.schedule([web])
        assert not result.unschedulable
        # plenty of room on n1, but the guard's anti-affinity repels web
        assert "web" not in result.existing_assignments
        assert len(result.new_groups) == 1

    def test_zone_anti_affinity_excludes_zone(self, catalog_items):
        """Zone-topology anti-affinity: the new group's zones exclude the
        zone hosting the matching pod."""
        web = small("web", labels={"app": "web"})
        node = ExistingNode(
            name="n1",
            labels={wk.ZONE_LABEL: "us-central-1c"},
            allocatable=Resources({"cpu": "600m", "memory": "1100Mi", "pods": 8}),
        )
        node.used = Resources({"cpu": "600m", "memory": "1100Mi"})
        hater = small(
            "hater", affinity_terms=affinity({"app": "web"}, key=wk.ZONE_LABEL, anti=True)
        )
        _, sched = mk_sched(catalog_items, existing=[node], pods_by_node={"n1": [web]})
        result = sched.schedule([hater])
        assert not result.unschedulable
        zreq = result.new_groups[0].requirements.get(wk.ZONE_LABEL)
        assert zreq is not None
        assert not zreq.matches("us-central-1c")

    def test_anti_affinity_blocks_join_not_just_open(self, catalog_items):
        """An anti-affine pod refuses to JOIN a group holding a match."""
        web = small("web", labels={"app": "web"})
        hater = small(
            "hater", labels={"app": "hater"},
            affinity_terms=affinity({"app": "web"}, anti=True),
        )
        _, sched = mk_sched(catalog_items)
        result = sched.schedule([web, hater])
        assert not result.unschedulable
        for g in result.new_groups:
            names = {p.metadata.name for p in g.pods}
            assert names != {"web", "hater"}


class TestRoutingOnMergedClasses:
    """Round 5: the canonical class key embeds oracle_suffix_rank, so a
    constrained pod can no longer merge behind a plain representative --
    the partitions align exactly with class boundaries. Routing then
    carves the constrained classes to the oracle SUFFIX, UNLESS the two
    sides could couple (label targets, spread selectors, or a shared
    rank-stripped envelope key -- service._aff_partition_blocked)."""

    def test_same_shape_classes_no_longer_merge_and_block_the_carve(self, catalog_items):
        from karpenter_tpu.solver import encode
        from karpenter_tpu.solver.service import TPUSolver

        plain = small("plain")
        anti = small(
            "anti", labels={"app": "x"},
            affinity_terms=affinity({"app": "x"}, anti=True),
        )
        classes = encode.group_pods([plain, anti])
        # the rank keeps them apart even at identical size/selector/
        # tolerations...
        assert len(classes) == 2
        assert [pc.has_affinity for pc in classes] == [False, True]
        # ...and the plain class sorts FIRST (suffix rank leads the order)
        assert classes[0].pods[0] is plain
        _, sched = mk_sched(catalog_items)
        # same shape means a shared rank-stripped envelope key: the carve
        # is blocked and the whole batch takes one oracle pass, which
        # preserves the follower-shares-anchor-envelope behavior
        assert not TPUSolver.supports(sched, [plain, anti])

    def test_multi_term_node_affinity_same_shape_blocks_the_carve(self, catalog_items):
        from karpenter_tpu.scheduling import Operator, Requirement
        from karpenter_tpu.solver import encode
        from karpenter_tpu.solver.service import TPUSolver

        plain = small("plain")
        multi = small(
            "multi",
            node_affinity_terms=[
                [Requirement(wk.ZONE_LABEL, Operator.IN, ["us-central-1a"])],
                [Requirement(wk.ZONE_LABEL, Operator.IN, ["us-central-1b"])],
            ],
        )
        classes = encode.group_pods([plain, multi])
        assert any(pc.multi_node_affinity for pc in classes)
        _, sched = mk_sched(catalog_items)
        # DIFFERENT requirements (the multi pod's class carries its first
        # term's zone pin): no envelope collision, no label coupling --
        # the carve is allowed and supports() now says True
        assert TPUSolver.supports(sched, [plain, multi])

    def test_distinct_shape_affinity_carves_to_suffix(self, catalog_items):
        """The payoff case: an affinity pod of a DIFFERENT shape whose
        selector targets only its own partition rides the suffix; the
        split result equals one full oracle pass exactly."""
        from karpenter_tpu.solver.service import TPUSolver

        web = small("web", labels={"app": "web"})
        follower = Pod(
            "follower",
            requests=Resources({"cpu": "250m", "memory": "512Mi"}),
            labels={"tier": "cache"},
            affinity_terms=affinity({"tier": "cache"}),
        )
        _, sched = mk_sched(catalog_items)
        assert TPUSolver.supports(sched, [web, follower])
        solver = TPUSolver(g_max=64)
        _, sched2 = mk_sched(catalog_items)
        split = solver.schedule(sched2, [web, follower])
        _, sched3 = mk_sched(catalog_items)
        full = sched3.schedule([web, follower])
        assert not split.unschedulable and not full.unschedulable
        sig = lambda r: sorted(
            (sorted(p.metadata.name for p in g.pods),
             sorted(it.name for it in g.instance_types))
            for g in r.new_groups
        )
        assert sig(split) == sig(full)


class TestSpecTokenSafety:
    """Round-3 review finding: a caller that mutates and reuses one spec
    dict across Pod constructions must not produce falsely-shared grouping
    tokens (the id()s coincide; the content fingerprint must not)."""

    def test_mutated_reused_selector_does_not_merge(self):
        from karpenter_tpu.solver import encode

        shared_req = Resources({"cpu": "500m", "memory": "1Gi"})
        sel = {}
        pods = []
        for z in ("zone-a", "zone-b"):
            sel[wk.ZONE_LABEL] = z   # same dict object, mutated in place
            pods.append(Pod(f"p-{z}", requests=shared_req, node_selector=sel))
        classes = encode.group_pods(pods)
        # the second pod's signature (computed from its COPIED selector)
        # must not be absorbed into the first pod's class via the token
        assert len(classes) == 2
        zones = sorted(pc.pods[0].node_selector[wk.ZONE_LABEL] for pc in classes)
        assert zones == ["zone-a", "zone-b"]

    def test_mutated_second_key_does_not_merge(self):
        """Round-3 review: the fingerprint must cover ALL selector items,
        not just the first -- mutating a non-first key of a reused dict
        must still split the tokens."""
        from karpenter_tpu.solver import encode

        shared_req = Resources({"cpu": "500m", "memory": "1Gi"})
        sel = {"disktype": "ssd"}
        pods = []
        for z in ("zone-a", "zone-b"):
            sel[wk.ZONE_LABEL] = z   # second key of the same dict object
            pods.append(Pod(f"q-{z}", requests=shared_req, node_selector=sel))
        classes = encode.group_pods(pods)
        assert len(classes) == 2


class TestPreferenceRelaxation:
    """Preferred node affinity via the core's preference-relaxation model:
    preferences apply as requirements; a pod that cannot place drops the
    lowest-weight preference and retries, ending with none."""

    def _prefs(self, *pairs):
        from karpenter_tpu.scheduling import Operator, Requirement

        return [
            (w, [Requirement(key, Operator.IN, [val])]) for (w, key, val) in pairs
        ]

    def test_satisfiable_preference_is_honored(self, catalog_items):
        zone = sorted({o.zone for it in catalog_items for o in it.available_offerings()})[0]
        p = small("pref", preferred_node_affinity_terms=self._prefs((10, wk.ZONE_LABEL, zone)))
        _, sched = mk_sched(catalog_items)
        result = sched.schedule([p])
        assert not result.unschedulable
        g = result.new_groups[0]
        zreq = g.requirements.get(wk.ZONE_LABEL)
        assert zreq is not None and zreq.matches(zone) and not zreq.matches("other")

    def test_unsatisfiable_preference_relaxes(self, catalog_items):
        p = small(
            "wishful",
            preferred_node_affinity_terms=self._prefs((10, wk.ZONE_LABEL, "zone-on-the-moon")),
        )
        _, sched = mk_sched(catalog_items)
        result = sched.schedule([p])
        assert not result.unschedulable, "preference must relax, not block"

    def test_lowest_weight_drops_first(self, catalog_items):
        zones = sorted({o.zone for it in catalog_items for o in it.available_offerings()})
        p = small(
            "ranked",
            preferred_node_affinity_terms=self._prefs(
                (100, wk.ZONE_LABEL, zones[0]),          # strong: satisfiable
                (1, wk.ZONE_LABEL, "zone-on-the-moon"),  # weak: impossible
            ),
        )
        _, sched = mk_sched(catalog_items)
        result = sched.schedule([p])
        assert not result.unschedulable
        zreq = result.new_groups[0].requirements.get(wk.ZONE_LABEL)
        # the weak impossible preference was dropped; the strong one held
        assert zreq is not None and zreq.matches(zones[0])

    def test_preference_pods_route_to_oracle(self, catalog_items):
        from karpenter_tpu.solver.service import TPUSolver

        p = small("pref2", preferred_node_affinity_terms=self._prefs((1, wk.ARCH_LABEL, "arm64")))
        _, sched = mk_sched(catalog_items)
        assert not TPUSolver.supports(sched, [p])
        # end-to-end through the router: the preference is honored
        result = TPUSolver(g_max=64).schedule(sched, [p])
        assert not result.unschedulable
        areq = result.new_groups[0].requirements.get(wk.ARCH_LABEL)
        assert areq is not None and areq.matches("arm64") and not areq.matches("amd64")

    def test_preferred_pod_affinity_colocates(self, catalog_items):
        """A follower with WEIGHTED (preferred) pod affinity to app=web
        lands in the web pod's zone -- the preference applies as a
        requirement at full strength first (VERDICT round 3, item 5)."""
        zones = sorted({o.zone for it in catalog_items for o in it.available_offerings()})
        web = small("web", labels={"app": "web"},
                    node_selector={wk.ZONE_LABEL: zones[2]})
        follower = small(
            "follower",
            preferred_affinity_terms=[
                (10, PodAffinityTerm(label_selector={"app": "web"}, topology_key=wk.ZONE_LABEL))
            ],
        )
        _, sched = mk_sched(catalog_items)
        result = sched.schedule([web, follower])
        assert not result.unschedulable
        by_pod = {p.metadata.name: g for g in result.new_groups for p in g.pods}
        fol_zone = by_pod["follower"].requirements.get(wk.ZONE_LABEL)
        assert fol_zone is not None and fol_zone.matches(zones[2]), (
            "the preference must pull the follower into the web pod's zone"
        )

    def test_preferred_pod_affinity_relaxes_when_impossible(self, catalog_items):
        """Preferred affinity to a workload that exists nowhere must drop,
        not block (required affinity WOULD block here: no match anywhere
        and the pod does not match its own selector)."""
        p = small(
            "wishful",
            preferred_affinity_terms=[
                (10, PodAffinityTerm(label_selector={"app": "ghost"}, topology_key=wk.ZONE_LABEL))
            ],
        )
        _, sched = mk_sched(catalog_items)
        result = sched.schedule([p])
        assert not result.unschedulable, "pod-affinity preference must relax, not block"
        # the required twin DOES block -- the relaxation is the difference
        q = small("wishful-req", affinity_terms=affinity({"app": "ghost"}, key=wk.ZONE_LABEL))
        _, sched2 = mk_sched(catalog_items)
        assert sched2.schedule([q]).unschedulable

    def test_preferred_anti_affinity_separates(self, catalog_items):
        """Two replicas with preferred zone anti-affinity to their own
        label land in DIFFERENT zones (max-fit would co-pack them)."""
        zones = sorted({o.zone for it in catalog_items for o in it.available_offerings()})
        # anchor is bigger so FFD's size-descending order places it first
        anchor = Pod("r0", requests=Resources({"cpu": "2", "memory": "4Gi"}),
                     labels={"app": "spready"},
                     node_selector={wk.ZONE_LABEL: zones[0]})
        repelled = small(
            "r1",
            labels={"app": "spready"},
            preferred_affinity_terms=[
                (10, PodAffinityTerm(label_selector={"app": "spready"},
                                     topology_key=wk.ZONE_LABEL, anti=True))
            ],
        )
        _, sched = mk_sched(catalog_items)
        result = sched.schedule([anchor, repelled])
        assert not result.unschedulable
        by_pod = {p.metadata.name: g for g in result.new_groups for p in g.pods}
        z1 = by_pod["r1"].requirements.get(wk.ZONE_LABEL)
        assert z1 is not None and not z1.matches(zones[0]), (
            "preferred anti must steer the replica out of the anchor's zone"
        )

    def test_conflicting_preferences_drop_lowest_weight(self, catalog_items):
        """Strong colocation preference + weak anti-preference to the SAME
        workload: the pair is contradictory, the weak one drops, and the
        pod colocates."""
        zones = sorted({o.zone for it in catalog_items for o in it.available_offerings()})
        web = small("web", labels={"app": "web"},
                    node_selector={wk.ZONE_LABEL: zones[1]})
        torn = small(
            "torn",
            preferred_affinity_terms=[
                (100, PodAffinityTerm(label_selector={"app": "web"}, topology_key=wk.ZONE_LABEL)),
                (1, PodAffinityTerm(label_selector={"app": "web"},
                                    topology_key=wk.ZONE_LABEL, anti=True)),
            ],
        )
        _, sched = mk_sched(catalog_items)
        result = sched.schedule([web, torn])
        assert not result.unschedulable
        by_pod = {p.metadata.name: g for g in result.new_groups for p in g.pods}
        torn_zone = by_pod["torn"].requirements.get(wk.ZONE_LABEL)
        assert torn_zone is not None and torn_zone.matches(zones[1]), (
            "the strong colocation preference must win"
        )

    def test_mixed_node_and_pod_preference_ladder(self, catalog_items):
        """One ladder over BOTH kinds: a strong satisfiable node preference
        survives while a weak impossible pod preference drops."""
        from karpenter_tpu.scheduling import Operator, Requirement

        zones = sorted({o.zone for it in catalog_items for o in it.available_offerings()})
        p = small(
            "mixed",
            preferred_node_affinity_terms=[
                (100, [Requirement(wk.ZONE_LABEL, Operator.IN, [zones[1]])])
            ],
            preferred_affinity_terms=[
                (1, PodAffinityTerm(label_selector={"app": "ghost"}, topology_key=wk.ZONE_LABEL))
            ],
        )
        _, sched = mk_sched(catalog_items)
        result = sched.schedule([p])
        assert not result.unschedulable
        zreq = result.new_groups[0].requirements.get(wk.ZONE_LABEL)
        assert zreq is not None and zreq.matches(zones[1])

    def test_preferred_pod_affinity_routes_to_oracle(self, catalog_items):
        from karpenter_tpu.solver.service import TPUSolver

        web = small("web", labels={"app": "web"})
        p = small(
            "pref-pod",
            preferred_affinity_terms=[
                (5, PodAffinityTerm(label_selector={"app": "web"}, topology_key=wk.ZONE_LABEL))
            ],
        )
        _, sched = mk_sched(catalog_items)
        assert not TPUSolver.supports(sched, [web, p])
        result = TPUSolver(g_max=64).schedule(sched, [web, p])
        assert not result.unschedulable

    def test_identical_preference_pods_share_one_group_via_direct_oracle(self, catalog_items):
        """Round-3 review repro: the oracle called DIRECTLY (provisioner
        without solver, disruption simulation) must not let a preference
        variant pollute the memoized grouping signature -- two identical
        preference pods share one price-envelope class and pack onto ONE
        node, exactly like their plain twins."""
        from karpenter_tpu.scheduling import Operator, Requirement

        zones = sorted({o.zone for it in catalog_items for o in it.available_offerings()})
        prefs = [(10, [Requirement(wk.ZONE_LABEL, Operator.IN, [zones[0]])])]
        pods = [
            small(f"twin-{i}", preferred_node_affinity_terms=prefs) for i in range(2)
        ]
        plain = [small(f"plain-{i}") for i in range(2)]
        _, sched_pref = mk_sched(catalog_items)
        _, sched_plain = mk_sched(catalog_items)
        r_pref = sched_pref.schedule(pods)
        r_plain = sched_plain.schedule(plain)
        assert not r_pref.unschedulable
        assert len(r_pref.new_groups) == len(r_plain.new_groups)
        # and the signature memo still reflects the ORIGINAL (pref-free
        # required affinity) spec
        for p in pods:
            assert p._group_sig is not None and p._group_sig[2] == ()


class TestAffinityCarveFuzz:
    """Round-5 differential tier for the oracle-suffix carve
    (VERDICT r4 item 2): batches with a few percent affinity/preference
    pods must (a) keep the plain majority on the device path and (b)
    produce EXACTLY the full oracle's result -- the carve is an execution
    strategy, not a semantic fork."""

    @staticmethod
    def _mixed_batch(catalog_items, seed, n_plain_templates=8, replicas=6):
        import numpy as np

        from karpenter_tpu.scheduling import Toleration

        rng = np.random.default_rng(77_000 + seed)
        zones = sorted({o.zone for it in catalog_items for o in it.available_offerings()})
        pods = []
        # plain majority: cpu values drawn from a set DISJOINT from the
        # affinity templates' below, so rank-stripped class keys can never
        # collide and the carve is guaranteed (the blocked case has its
        # own test)
        for t in range(n_plain_templates):
            cpu_m = int(rng.choice([100, 250, 500, 1000, 2000, 3000]))
            mem_mi = int(rng.choice([128, 512, 1024, 4096]))
            selector = {}
            u = rng.random()
            if u < 0.2:
                selector[wk.ZONE_LABEL] = zones[int(rng.integers(0, len(zones)))]
            elif u < 0.3:
                selector[wk.CAPACITY_TYPE_LABEL] = "on-demand"
            tolerations = []
            if rng.random() < 0.15:
                tolerations.append(Toleration(key="dedicated", operator="Exists"))
            for i in range(int(rng.integers(2, replicas + 2))):
                pods.append(Pod(
                    f"c{seed}-p{t}-{i}",
                    requests=Resources.from_base_units(
                        {"cpu": float(cpu_m), "memory": float(mem_mi) * 2**20}),
                    node_selector=selector,
                    tolerations=tolerations,
                    labels={"app": f"plain-{t}"},
                ))
        # constrained minority (~2-8% of the batch): anchors + followers +
        # anti-affinity + preferences, selectors targeting ONLY labels the
        # constrained partition carries
        n_aff = max(1, len(pods) // int(rng.integers(12, 40)))
        aff_cpus = [150.0, 350.0, 650.0]
        for a in range(n_aff):
            kind = int(rng.integers(0, 4))
            cpu = float(aff_cpus[a % len(aff_cpus)])
            reqs = Resources.from_base_units({"cpu": cpu, "memory": 256.0 * 2**20})
            tier = f"aff-{a % 3}"
            if kind == 0:      # anchor+its own label; follower affinity to tier
                pods.append(Pod(
                    f"c{seed}-a{a}", requests=reqs, labels={"tier": tier},
                    affinity_terms=[PodAffinityTerm(
                        label_selector={"tier": tier}, topology_key=wk.HOSTNAME_LABEL)],
                ))
            elif kind == 1:    # zone anti-affinity within the minority
                pods.append(Pod(
                    f"c{seed}-a{a}", requests=reqs, labels={"tier": tier},
                    affinity_terms=[PodAffinityTerm(
                        label_selector={"tier": tier}, topology_key=wk.ZONE_LABEL,
                        anti=True)],
                ))
            elif kind == 2:    # weighted zone preference (relaxation ladder)
                from karpenter_tpu.scheduling import Operator as Op, Requirement

                pods.append(Pod(
                    f"c{seed}-a{a}", requests=reqs, labels={"tier": tier},
                    preferred_node_affinity_terms=[
                        (10, [Requirement(wk.ZONE_LABEL, Op.IN,
                                          [zones[a % len(zones)]])])],
                ))
            else:              # OR-of-terms node affinity
                from karpenter_tpu.scheduling import Operator as Op, Requirement

                pods.append(Pod(
                    f"c{seed}-a{a}", requests=reqs, labels={"tier": tier},
                    node_affinity_terms=[
                        [Requirement(wk.ZONE_LABEL, Op.IN, [zones[0]])],
                        [Requirement(wk.ZONE_LABEL, Op.IN, [zones[-1]])],
                    ],
                ))
        return pods

    @pytest.mark.parametrize("seed", range(8))
    def test_split_matches_full_oracle_exactly(self, catalog_items, seed):
        from karpenter_tpu.solver.service import TPUSolver

        pods = self._mixed_batch(catalog_items, seed)
        solver = TPUSolver(g_max=256)
        _, sched_split = mk_sched(catalog_items)
        split = solver.schedule(sched_split, list(pods))
        assert solver.last_route["path"] == "device+suffix", solver.last_route
        total = solver.last_route["device_pods"] + solver.last_route["oracle_pods"]
        assert solver.last_route["device_pods"] >= 0.9 * total, solver.last_route
        _, sched_full = mk_sched(catalog_items)
        full = sched_full.schedule(list(pods))
        assert set(split.unschedulable) == set(full.unschedulable), f"seed {seed}"
        assert _aff_sig(split) == _aff_sig(full), f"seed {seed}"

    def test_pool_limits_block_the_carve(self, catalog_items):
        """The oracle charges a group's smallest candidate at OPEN time;
        the device guard charges the smallest FINAL survivor. The charges
        can differ, so limits force the whole batch onto one oracle pass
        (round-5 review finding)."""
        from karpenter_tpu.solver.service import TPUSolver

        pool = NodePool("default", limits=Resources({"cpu": "2000"}))
        zones = {o.zone for it in catalog_items for o in it.available_offerings()}
        sched = Scheduler(
            nodepools=[pool], instance_types={"default": catalog_items},
            zones=zones,
        )
        pods = [small(f"w-{i}") for i in range(4)] + [Pod(
            "aff", requests=Resources({"cpu": "250m", "memory": "512Mi"}),
            labels={"t": "x"},
            affinity_terms=affinity({"t": "x"}),
        )]
        solver = TPUSolver(g_max=64)
        result = solver.schedule(sched, pods)
        assert solver.last_route["path"] == "oracle", solver.last_route
        assert not result.unschedulable

    def test_label_coupling_blocks_the_carve(self, catalog_items):
        """A follower whose selector matches PLAIN pods' labels must push
        the whole batch onto one oracle pass (the suffix never sees the
        device pods' labels, so carving would mis-schedule it)."""
        from karpenter_tpu.solver.service import TPUSolver

        web = [small(f"web-{i}", labels={"app": "web"}) for i in range(4)]
        follower = Pod(
            "follower",
            requests=Resources({"cpu": "250m", "memory": "512Mi"}),
            affinity_terms=affinity({"app": "web"}),
        )
        solver = TPUSolver(g_max=64)
        _, sched = mk_sched(catalog_items)
        result = solver.schedule(sched, web + [follower])
        assert solver.last_route["path"] == "oracle", solver.last_route
        assert not result.unschedulable
        # and the oracle co-located the follower with a web pod
        g_follower = next(g for g in result.new_groups
                          if any(p.metadata.name == "follower" for p in g.pods))
        assert any(p.metadata.name.startswith("web") for p in g_follower.pods)


def _aff_sig(result):
    """Packing signature incl. surviving types (envelope equality)."""
    return sorted(
        (tuple(sorted(p.metadata.name for p in g.pods)),
         tuple(sorted(it.name for it in g.instance_types)))
        for g in result.new_groups
    )


@pytest.mark.skipif(
    not __import__("os").environ.get("KARPENTER_TPU_FUZZ_EXTENDED"),
    reason="extended differential sweep: set KARPENTER_TPU_FUZZ_EXTENDED=1",
)
class TestAffinityCarveFuzzExtended:
    """Wider carve sweep behind make fuzz-extended, with existing nodes in
    the mix (the suffix packs onto the device pass's remaining capacity)."""

    @pytest.mark.parametrize("seed", range(8, 40))
    def test_sweep(self, catalog_items, seed):
        TestAffinityCarveFuzz().test_split_matches_full_oracle_exactly(
            catalog_items, seed)

    @pytest.mark.parametrize("seed", range(6))
    def test_with_existing_nodes(self, catalog_items, seed):
        import copy

        import numpy as np

        from karpenter_tpu.scheduling import resources as res
        from karpenter_tpu.solver.service import TPUSolver

        rng = np.random.default_rng(88_000 + seed)
        zones = sorted({o.zone for it in catalog_items for o in it.available_offerings()})
        pods = TestAffinityCarveFuzz._mixed_batch(catalog_items, 500 + seed)
        existing = []
        for ni in range(int(rng.integers(1, 4))):
            existing.append(ExistingNode(
                name=f"e{seed}-n{ni}",
                labels={wk.ZONE_LABEL: zones[int(rng.integers(0, len(zones)))],
                        wk.ARCH_LABEL: "amd64"},
                allocatable=Resources.from_base_units(
                    {res.CPU: 4000.0, res.MEMORY: 8.0 * 2**30, res.PODS: 20}),
            ))

        def mk(items):
            pool = NodePool("default")
            return Scheduler(
                nodepools=[pool], instance_types={pool.name: items},
                existing_nodes=copy.deepcopy(existing), zones=set(zones),
            )

        solver = TPUSolver(g_max=256)
        split = solver.schedule(mk(catalog_items), list(pods))
        assert solver.last_route["path"] == "device+suffix", solver.last_route
        full = mk(catalog_items).schedule(list(pods))
        assert set(split.unschedulable) == set(full.unschedulable), f"seed {seed}"
        from collections import Counter
        assert Counter(split.existing_assignments.items()) == Counter(
            full.existing_assignments.items()), f"seed {seed}"
        assert _aff_sig(split) == _aff_sig(full), f"seed {seed}"
