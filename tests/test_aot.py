"""AOT compile-cache subsystem tests (solver/aot.py): the armed
executable path must be BIT-IDENTICAL to the jit path it shadows, every
failure mode must land on a counted typed rung that falls back to JIT,
and the versioned cache layout must survive restarts and sweep stale
versions -- the zero-compile cold-start contract, asserted end to end
(subprocess restart drill included)."""
import json
import os
import pickle
import subprocess
import sys

import numpy as np
import pytest

import jax

from karpenter_tpu.apis import NodePool, Pod, TPUNodeClass
from karpenter_tpu.apis.nodeclass import SubnetStatus
from karpenter_tpu.cache.unavailable_offerings import UnavailableOfferings
from karpenter_tpu.kwok.cloud import FakeCloud
from karpenter_tpu.providers.instancetype import gen_catalog
from karpenter_tpu.providers.instancetype.offerings import OfferingsBuilder
from karpenter_tpu.providers.instancetype.provider import InstanceTypeProvider
from karpenter_tpu.providers.instancetype.types import Resolver
from karpenter_tpu.providers.pricing import PricingProvider
from karpenter_tpu.scheduling import Resources
from karpenter_tpu.solver import aot
from karpenter_tpu.solver.service import TPUSolver

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def catalog_items():
    cloud = FakeCloud()
    prov = InstanceTypeProvider(
        cloud,
        Resolver(gen_catalog.REGION),
        OfferingsBuilder(
            PricingProvider(cloud, cloud, gen_catalog.REGION),
            UnavailableOfferings(),
            {z.name: z.zone_id for z in gen_catalog.ZONES},
        ),
        UnavailableOfferings(),
    )
    nc = TPUNodeClass("default")
    nc.status_subnets = [SubnetStatus(s.id, s.zone, s.zone_id) for s in cloud.describe_subnets()]
    return prov.list(nc)


def make_pods(n=60):
    """A deterministic small workload with a handful of distinct specs
    (few classes -> the smallest c_pad bucket -> cheap compiles)."""
    pods = []
    shapes = [("1", 2), ("2", 4), ("4", 8), ("500m", 1)]
    for i in range(n):
        cpu, mem = shapes[i % len(shapes)]
        pods.append(Pod(f"p{i}", requests=Resources({"cpu": cpu, "memory": f"{mem}Gi"})))
    return pods


def decisions_sig(result):
    """Order-insensitive digest of the placement decision (the quantity
    the AOT differential pins)."""
    return sorted(
        (sorted(it.name for it in g.instance_types),
         sorted(p.metadata.name for p in g.pods))
        for g in result.new_groups
    )


@pytest.fixture(scope="module")
def armed_world(catalog_items, tmp_path_factory):
    """One shared armed-AOT world: a pure-JIT solve, then a second solver
    whose plan compiled + serialized the same shapes, solved the same
    pods. Module-scoped -- the compiles are the expensive part and every
    assertion reads the same world."""
    exec_dir = str(tmp_path_factory.mktemp("aot-exec"))
    pool = NodePool("default")
    pods = make_pods()

    jit_solver = TPUSolver(g_max=64)
    result_jit = jit_solver.solve(pool, catalog_items, pods)

    solver = TPUSolver(g_max=64)
    # capture the c_pad the production dispatch uses so the plan's pads
    # cover exactly the hot bucket (what bench's coldstart cold child does)
    pad_cell = []
    orig = solver._dispatch_bound

    def cap(inp, placed, *a, **kw):
        pad_cell.append(int(placed.shape[0]))
        return orig(inp, placed, *a, **kw)

    solver._dispatch_bound = cap
    try:
        solver.solve(pool, catalog_items, pods)
    finally:
        solver._dispatch_bound = orig
    pad = pad_cell[0]

    mgr = solver.enable_aot(exec_dir, serialize=True, duty=1.0, pads=(pad,))
    plan = mgr.run_plan(solver._catalog(catalog_items), throttle=False)
    d0 = aot.AOT_DISPATCHES.value(entry="ffd_solve_fused") + aot.AOT_DISPATCHES.value(
        entry="fractional_price_bound")
    result_aot = solver.solve(pool, catalog_items, pods)
    d1 = aot.AOT_DISPATCHES.value(entry="ffd_solve_fused") + aot.AOT_DISPATCHES.value(
        entry="fractional_price_bound")
    return {
        "exec_dir": exec_dir, "pool": pool, "pods": pods, "pad": pad,
        "solver": solver, "mgr": mgr, "plan": plan,
        "result_jit": result_jit, "result_aot": result_aot,
        "aot_dispatch_delta": d1 - d0,
    }


class TestKeysAndLayout:
    def test_exec_key_stability(self):
        args = (np.zeros((4, 8), np.float32), np.zeros((4,), np.int32))
        statics = {"g_max": 64, "objective": "price"}
        k1 = aot.exec_key("ffd_solve_fused", statics, args, "fp")
        k2 = aot.exec_key("ffd_solve_fused", dict(statics), tuple(args), "fp")
        assert k1 == k2
        # every key component must move the key
        assert k1 != aot.exec_key("other_entry", statics, args, "fp")
        assert k1 != aot.exec_key("ffd_solve_fused", {**statics, "g_max": 128}, args, "fp")
        assert k1 != aot.exec_key(
            "ffd_solve_fused", statics, (np.zeros((8, 8), np.float32), args[1]), "fp")
        assert k1 != aot.exec_key("ffd_solve_fused", statics, args, "fp2")

    def test_fingerprint_pins_runtime(self):
        import jaxlib

        fp = aot.fingerprint()
        assert jax.__version__ in fp
        assert jaxlib.__version__ in fp
        assert jax.default_backend() in fp
        assert f"{len(jax.devices())}x" in fp
        # filesystem-safe: used verbatim as a directory name
        assert "/" not in fp and " " not in fp

    def test_sweep_stale_keeps_current(self, tmp_path):
        root = str(tmp_path / "cache")
        fp = aot.fingerprint()
        for name in (fp, "jax0.0.0-stale-a", "jax0.0.0-stale-b"):
            os.makedirs(os.path.join(root, name, "xla"))
        # loose files at the root are inert, never swept
        open(os.path.join(root, "legacy.bin"), "wb").close()
        before = aot.AOT_SWEPT_DIRS.value()
        home = aot.prepare_cache(root)
        assert home == os.path.join(root, fp)
        assert sorted(os.listdir(root)) == [fp, "legacy.bin"]
        assert aot.AOT_SWEPT_DIRS.value() - before == 2
        assert os.path.isdir(os.path.join(home, "exec"))

    def test_resolve_root_precedence(self, monkeypatch):
        monkeypatch.setenv(aot.CACHE_ENV, "/env/root")
        monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", "/jax/root")
        assert aot.resolve_root("/explicit") == "/explicit"
        assert aot.resolve_root() == "/env/root"
        monkeypatch.delenv(aot.CACHE_ENV)
        assert aot.resolve_root() == "/jax/root"

    def test_duty_clamped(self):
        solver = TPUSolver(g_max=16)
        assert aot.AotManager(solver, duty=0.0).duty == 0.005
        assert aot.AotManager(solver, duty=7.0).duty == 1.0


class TestBitIdentity:
    def test_aot_dispatch_hits(self, armed_world):
        """The armed table serves the production solve for both tier-0
        families -- the precompile actually lands on the dispatch seam."""
        assert armed_world["plan"]["compiled"] >= 2
        assert armed_world["aot_dispatch_delta"] >= 2

    def test_aot_equals_jit_decisions(self, armed_world):
        """The differential: AOT never changes a decision, only who
        compiles it."""
        assert decisions_sig(armed_world["result_aot"]) == decisions_sig(
            armed_world["result_jit"])
        assert (armed_world["result_aot"].unschedulable
                == armed_world["result_jit"].unschedulable)

    def test_coverage_gauge_full(self, armed_world):
        for entry in ("ffd_solve_fused", "fractional_price_bound"):
            assert aot.AOT_PRECOMPILED_FRACTION.value(entry=entry) == 1.0

    def test_pack_existing_repack_armed(self, armed_world):
        """The disruption stage's pack-existing floor shape (S=1, C/N at
        their bucket floors) rides an armed executable bit-identically --
        what makes a restarted OPERATOR settle, not just the bench solve
        path, run zero traces."""
        import numpy as np

        from karpenter_tpu.solver import encode
        from karpenter_tpu.solver.disrupt import kernel as disrupt_kernel

        solver = armed_world["solver"]
        # floor shapes exactly as service._pack_existing builds them
        Cp = int(encode.bucket(1, solver.c_pad_min))
        N = 16
        R = encode.R
        rng = np.random.default_rng(3)
        headroom = rng.random((N, R)).astype(np.float32)
        feas = rng.random((Cp, N)) > 0.5
        req = rng.random((Cp, R)).astype(np.float32)
        member = rng.integers(0, 3, (1, Cp)).astype(np.int32)
        excl = np.zeros((1, N), dtype=bool)

        d0 = aot.AOT_DISPATCHES.value(entry="disrupt_repack")
        out_aot = solver._dispatch_disrupt_repack(
            headroom, feas, req, member, excl)
        d1 = aot.AOT_DISPATCHES.value(entry="disrupt_repack")
        assert d1 - d0 == 1, "floor-shape repack must ride the armed exec"

        out_jit = disrupt_kernel.disrupt_repack(
            headroom, feas, req, member, excl)
        for a, b in zip(out_aot, out_jit):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_describe_surface(self, armed_world):
        doc = armed_world["solver"].describe_aot()
        assert doc["fingerprint"] == aot.fingerprint()
        assert doc["exec_dir"] == armed_world["exec_dir"]
        assert doc["armed"] >= 2
        for entry in ("ffd_solve_fused", "fractional_price_bound"):
            assert doc["entries"][entry]["armed"] >= 1
            assert doc["entries"][entry]["fraction"] == 1.0
        assert doc["store"]["artifacts"] >= 2

    def test_debug_endpoint_registered(self):
        from karpenter_tpu.operator import health

        assert "/debug/aot" in health.DEBUG_ENDPOINTS


class TestExecStore:
    def test_serialized_artifacts_on_disk(self, armed_world):
        store = armed_world["mgr"].store
        st = store.stats()
        assert st["artifacts"] >= 2
        assert st["bytes"] > 0

    def test_restart_arm_from_store(self, armed_world, catalog_items):
        """A NEW manager over the same exec dir arms from disk (the
        in-process restart path) and its solve is bit-identical."""
        before = aot.AOT_LOADED.value(entry="ffd_solve_fused")
        solver = TPUSolver(g_max=64)
        solver.enable_aot(armed_world["exec_dir"], serialize=False,
                          duty=1.0, pads=(armed_world["pad"],))
        doc = solver.describe_aot()
        assert doc["loaded"] >= 2
        assert aot.AOT_LOADED.value(entry="ffd_solve_fused") - before >= 1
        result = solver.solve(armed_world["pool"], catalog_items,
                              armed_world["pods"])
        assert decisions_sig(result) == decisions_sig(armed_world["result_jit"])

    def test_corrupt_artifact_counted_and_unlinked(self, tmp_path):
        """Format corruption (garbage bytes, wrong version) is a counted
        deserialize rung AND the artifact is removed -- it would re-fail
        every restart."""
        store = aot.ExecStore(str(tmp_path / "exec"))
        fp = aot.fingerprint()
        garbage = store.artifact("deadbeef")
        with open(garbage, "wb") as f:
            f.write(b"\x00not a pickle")
        stale = store.artifact("cafecafe")
        with open(stale, "wb") as f:
            pickle.dump({"v": -1}, f)
        before = aot.AOT_FALLBACKS.value(reason="deserialize")
        armed, failures = store.load_all(fp)
        assert armed == {} and failures == 2
        assert aot.AOT_FALLBACKS.value(reason="deserialize") - before == 2
        assert not os.path.exists(garbage) and not os.path.exists(stale)

    def test_backend_refusal_keeps_artifact(self, tmp_path):
        """A well-formed artifact whose PAYLOAD the backend refuses is
        counted but KEPT: the refusal can be process-state-dependent and
        a fresh process may load it fine."""
        store = aot.ExecStore(str(tmp_path / "exec"))
        fp = aot.fingerprint()
        path = store.artifact("feedface")
        with open(path, "wb") as f:
            pickle.dump({"v": aot._ARTIFACT_VERSION, "fingerprint": fp,
                         "entry": "ffd_solve_fused", "payload": b"bogus",
                         "in_tree": None, "out_tree": None}, f)
        armed, failures = store.load_all(fp)
        assert armed == {} and failures == 1
        assert os.path.exists(path)

    def test_wrong_fingerprint_rejected(self, tmp_path):
        store = aot.ExecStore(str(tmp_path / "exec"))
        path = store.artifact("0123abcd")
        with open(path, "wb") as f:
            pickle.dump({"v": aot._ARTIFACT_VERSION, "fingerprint": "other",
                         "entry": "e", "payload": b"", "in_tree": None,
                         "out_tree": None}, f)
        with pytest.raises(aot.AotDeserializeError) as ei:
            store.load_one(path, aot.fingerprint())
        assert ei.value.corrupt


class TestCorruptionFallback:
    def test_disarmed_on_dispatch_failure_decisions_identical(
            self, armed_world, catalog_items):
        """An armed executable that rejects a dispatch is disarmed on the
        counted rung and the tick finishes on JIT with the identical
        decision."""
        solver = TPUSolver(g_max=64)
        mgr = solver.enable_aot(None, serialize=False, duty=1.0,
                                pads=(armed_world["pad"],))
        mgr.run_plan(solver._catalog(catalog_items), throttle=False)

        class Rejecting:
            def __call__(self, *a, **k):
                raise RuntimeError("injected dispatch failure")

        with mgr._lock:
            keys = list(mgr._armed)
            for k in keys:
                mgr._armed[k] = Rejecting()
        before = aot.AOT_FALLBACKS.value(reason="dispatch")
        result = solver.solve(armed_world["pool"], catalog_items,
                              armed_world["pods"])
        assert aot.AOT_FALLBACKS.value(reason="dispatch") - before >= 1
        with mgr._lock:
            assert len(mgr._armed) < len(keys)  # disarmed, not retried
        assert decisions_sig(result) == decisions_sig(armed_world["result_jit"])


class TestMeshCoverage:
    def test_shrunk_layout_reshard_zero_compiles(self, catalog_items):
        """The degrade-ladder chapter: warm-call tasks cover the CURRENT
        mesh and every deterministic shrunk pow2 layout, so the first
        tick after a quarantine recompiles NOTHING and decides the same."""
        from karpenter_tpu.analysis import jax_witness

        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device virtual mesh (tests/conftest.py)")
        pool = NodePool("default")
        pods = make_pods()
        solver = TPUSolver(g_max=64, mesh=8)
        mgr = solver.enable_aot(None, serialize=False, duty=1.0, pads=(16,))
        r0 = solver.solve(pool, catalog_items, pods)
        plan = mgr.run_plan(solver._catalog(catalog_items), throttle=False)
        # full + shrunk(4) + shrunk(2), fused + bound each
        assert plan["tasks"] >= 6
        solver.mesh_engine.quarantine_worst_device("test-aot")
        st0 = jax_witness.stats()
        with jax_witness.hot("aot-reshard-tick"):
            r1 = solver.solve(pool, catalog_items, pods)
        st1 = jax_witness.stats()
        assert st1["compiles_total"] == st0["compiles_total"]
        assert st1["traces_total"] == st0["traces_total"]
        assert decisions_sig(r1) == decisions_sig(r0)


class TestAttribution:
    def test_witness_aot_phase_exemption(self):
        """Compiles under aot_phase() land on the AOT counters, never the
        hot-path compile counters a hot section would flag."""
        from karpenter_tpu.analysis import jax_witness

        @jax.jit
        def probe(x, salt):
            return x * 2.0 + salt

        st0 = jax_witness.stats()
        with jax_witness.aot_phase():
            probe(np.float32(3.0), 11.0).block_until_ready()
        st1 = jax_witness.stats()
        assert st1["aot_compiles_total"] > st0["aot_compiles_total"]
        assert st1["compiles_total"] == st0["compiles_total"]

    def test_jitstats_aot_columns(self):
        from karpenter_tpu.obs import jitstats

        jitstats.note_aot("test_entry_family", 0.25)
        row = jitstats.table()["test_entry_family"]
        assert row["aot_compiles"] >= 1
        assert row["aot_compile_ms"] >= 250.0
        # never mixed into the hot-path compile columns
        assert row["compiles"] == 0

    def test_cache_stats_keys(self):
        from karpenter_tpu.obs import jitstats

        cs = jitstats.cache_stats()
        assert set(cs) == {"hits", "misses", "bytes"}


class TestRestartDrill:
    def test_restart_zero_compiles_subprocess(self, tmp_path):
        """The headline contract end to end: process 1 solves cold with
        both cache layers enabled and serializes; process 2 restarts onto
        the same root and its first production tick must run ZERO
        compiles and ZERO traces, deciding identically."""
        script = os.path.join(ROOT, "tests", "fixtures", "aot_restart_child.py")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   XLA_FLAGS="--xla_force_host_platform_device_count=1",
                   KARPENTER_TPU_LOCK_WITNESS="0")
        root = str(tmp_path / "cache")
        outs = []
        for phase in ("serialize", "restart"):
            proc = subprocess.run(
                [sys.executable, script, phase, root],
                capture_output=True, text=True, timeout=420, env=env, cwd=ROOT)
            assert proc.returncode == 0, proc.stderr[-2000:]
            outs.append(json.loads(proc.stdout.strip().splitlines()[-1]))
        first, second = outs
        assert first["serialized"] >= 2
        assert second["loaded"] >= 2
        assert second["first_tick_compiles"] == 0
        assert second["first_tick_traces"] == 0
        assert second["decisions"] == first["decisions"]
