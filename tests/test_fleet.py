"""Fleet subsystem, half 1: the mesh-sharded PRODUCTION solve.

``sharded == unsharded`` is asserted the way ``host == wire`` is: the
same workload through the single-device entries and the MeshSolveEngine
must produce bit-identical decisions on every layout (flat 8-device and
2x4 hosts-x-types), through every surface -- the raw entries, the full
TPUSolver decision path, the pipelined begin/finish tick, and the rpc
sidecar with a mesh configured. The delta-epoch contracts hold per
shard: pressure eviction and mid-flight StaleEpochError restage exactly
as on one device.
"""
import os

import numpy as np
import pytest

import jax

from karpenter_tpu import metrics
from karpenter_tpu.apis import NodePool, Pod, TPUNodeClass, labels as wk
from karpenter_tpu.fleet.shard import MeshSolveEngine, parse_mesh_spec
from karpenter_tpu.obs import hbm as obs_hbm
from karpenter_tpu.parallel.mesh import make_mesh, make_mesh_2d
from karpenter_tpu.scheduling import Resources, Toleration
from karpenter_tpu.solver import encode, ffd
from karpenter_tpu.solver.rpc import SolverClient, SolverServer, StaleEpochError
from karpenter_tpu.solver.service import TPUSolver


@pytest.fixture(scope="module", params=["1d", "2x4"])
def engine(request):
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh (tests/conftest.py)")
    mesh = make_mesh(8) if request.param == "1d" else make_mesh_2d(2, 4)
    return MeshSolveEngine(mesh)


@pytest.fixture(scope="module")
def catalog_items():
    from karpenter_tpu.apis.nodeclass import SubnetStatus
    from karpenter_tpu.cache.unavailable_offerings import UnavailableOfferings
    from karpenter_tpu.kwok.cloud import FakeCloud
    from karpenter_tpu.providers.instancetype import gen_catalog
    from karpenter_tpu.providers.instancetype.offerings import OfferingsBuilder
    from karpenter_tpu.providers.instancetype.provider import InstanceTypeProvider
    from karpenter_tpu.providers.instancetype.types import Resolver
    from karpenter_tpu.providers.pricing import PricingProvider

    cloud = FakeCloud()
    prov = InstanceTypeProvider(
        cloud,
        Resolver(gen_catalog.REGION),
        OfferingsBuilder(
            PricingProvider(cloud, cloud, gen_catalog.REGION),
            UnavailableOfferings(),
            {z.name: z.zone_id for z in cloud.describe_zones()},
        ),
        UnavailableOfferings(),
    )
    nc = TPUNodeClass("default")
    nc.status_subnets = [SubnetStatus(s.id, s.zone, s.zone_id) for s in cloud.describe_subnets()]
    return prov.list(nc)


def mixed_pods(rng: np.random.Generator, n: int, salt: int = 0):
    shapes = [
        ("250m", "512Mi", None, ()),
        ("500m", "1Gi", None, ()),
        ("1", "2Gi", {wk.CAPACITY_TYPE_LABEL: wk.CAPACITY_TYPE_ON_DEMAND}, ()),
        ("2", "4Gi", {wk.ARCH_LABEL: "arm64"}, ()),
        ("500m", "2Gi", None, (Toleration(key="dedicated", operator="Exists"),)),
    ]
    pods = []
    for i in range(n):
        cpu, mem, sel, tol = shapes[int(rng.integers(0, len(shapes)))]
        pods.append(Pod(
            f"fleet-{salt}-{i}", requests=Resources({"cpu": cpu, "memory": mem}),
            node_selector=dict(sel) if sel else {}, tolerations=list(tol),
        ))
    return pods


def decision_sig(res):
    return (
        sorted(
            (tuple(sorted(p.metadata.name for p in g.pods)), g.instance_types[0].name)
            for g in res.new_groups
        ),
        sorted(res.existing_assignments.items()),
        sorted(res.unschedulable.items()),
    )


class TestMeshEngineBitIdentity:
    """Raw entries: dense / compact / fused, both objectives."""

    @pytest.mark.parametrize("objective", ["price", "fit"])
    def test_entries_match_single_device(self, engine, catalog_items, objective):
        catalog = encode.encode_catalog(catalog_items, k_pad=640)
        pool = NodePool("default")
        pods = mixed_pods(np.random.default_rng(5), 80)
        classes = encode.group_pods(pods, extra_requirements=pool.requirements())
        cs = encode.encode_classes(classes, catalog)
        inp, offsets, words = ffd.make_inputs(catalog, cs)
        kw = dict(g_max=64, word_offsets=offsets, words=words, objective=objective)
        single = ffd.ffd_solve(inp, **kw)
        meshed = engine.fetch(engine.solve_dense(inp, **kw))
        np.testing.assert_array_equal(np.asarray(single.take), meshed.take)
        np.testing.assert_array_equal(np.asarray(single.unplaced), meshed.unplaced)
        np.testing.assert_array_equal(np.asarray(single.gmask), meshed.gmask)
        np.testing.assert_array_equal(np.asarray(single.gzone), meshed.gzone)
        assert int(single.n_open) == int(meshed.n_open)

        nnz = ffd.nnz_budget(cs.c_pad, 64)
        csingle = ffd.ffd_solve_compact(inp, nnz_max=nnz, **kw)
        cmesh = engine.fetch(engine.solve_compact(inp, nnz_max=nnz, **kw))
        for name in ffd.CompactDecision._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(csingle, name)), np.asarray(getattr(cmesh, name)),
                err_msg=name,
            )
        fsingle = np.asarray(ffd.ffd_solve_fused(inp, nnz_max=nnz, **kw))
        fmesh = np.asarray(engine.solve_fused(inp, nnz_max=nnz, **kw))
        np.testing.assert_array_equal(fsingle, fmesh)

    def test_staged_catalog_reuse(self, engine, catalog_items):
        """Sharded staging: the staged shards feed make_inputs_staged and
        the solve matches the unstaged single-device result."""
        catalog = encode.encode_catalog(catalog_items, k_pad=640)
        staged, offsets, words = engine.stage_catalog(catalog)
        pods = mixed_pods(np.random.default_rng(6), 40)
        classes = encode.group_pods(pods)
        cs = encode.encode_classes(classes, catalog)
        inp_staged = ffd.make_inputs_staged(staged, cs)
        inp, o2, w2 = ffd.make_inputs(catalog, cs)
        assert (offsets, words) == (o2, w2)
        single = ffd.ffd_solve(inp, g_max=32, word_offsets=o2, words=w2)
        meshed = engine.fetch(
            engine.solve_dense(inp_staged, g_max=32, word_offsets=offsets, words=words)
        )
        np.testing.assert_array_equal(np.asarray(single.take), meshed.take)

    def test_repack_and_replace_match(self, engine):
        from karpenter_tpu.scheduling import resources as res
        from karpenter_tpu.solver.disrupt import kernel as disrupt_kernel

        rng = np.random.default_rng(9)
        N, C, S, R = 16, 8, 16, encode.R
        headroom = np.zeros((N, R), dtype=np.float32)
        headroom[:, res.AXIS_INDEX[res.CPU]] = rng.choice([2000, 4000, 8000], N)
        headroom[:, res.AXIS_INDEX[res.MEMORY]] = rng.choice([4096, 8192], N)
        headroom[:, res.AXIS_INDEX[res.PODS]] = 110
        req = np.zeros((C, R), dtype=np.float32)
        req[:, res.AXIS_INDEX[res.CPU]] = rng.choice([250, 500, 1000], C)
        req[:, res.AXIS_INDEX[res.MEMORY]] = rng.choice([256, 1024], C)
        req[:, res.AXIS_INDEX[res.PODS]] = 1
        feas = rng.random((C, N)) < 0.8
        member = rng.integers(0, 6, size=(S, C)).astype(np.int32)
        excl = rng.random((S, N)) < 0.2
        l1, t1 = disrupt_kernel.disrupt_repack(headroom, feas, req, member, excl)
        l2, t2 = engine.repack(headroom, feas, req, member, excl)
        np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
        np.testing.assert_array_equal(np.asarray(t1), np.asarray(t2))


class TestMeshProductionTick:
    """The promoted path: TPUSolver(mesh=...) through schedule-shaped
    solves, synchronous and pipelined, bit-identical to single-device."""

    def test_full_solve_bit_identical(self, engine, catalog_items):
        pool = NodePool("default")
        pods = mixed_pods(np.random.default_rng(11), 90)
        plain = TPUSolver(g_max=64).solve(pool, catalog_items, list(pods))
        meshy = TPUSolver(g_max=64, mesh=engine).solve(pool, catalog_items, list(pods))
        assert decision_sig(plain) == decision_sig(meshy)

    def test_pipelined_begin_finish(self, engine, catalog_items):
        pool = NodePool("default")
        solver = TPUSolver(g_max=64, mesh=engine)
        plain = TPUSolver(g_max=64)
        rng = np.random.default_rng(12)
        for tick in range(3):
            pods = mixed_pods(rng, 40 + 7 * tick, salt=tick)
            pending = solver.solve_begin(pool, catalog_items, list(pods))
            res = solver.solve_finish(pending)
            assert decision_sig(res) == decision_sig(
                plain.solve(pool, catalog_items, list(pods))
            ), f"tick {tick} diverged"

    def test_mesh_dispatch_counted(self, engine, catalog_items):
        before = metrics.MESH_DISPATCHES.value(entry="fused")
        TPUSolver(g_max=64, mesh=engine).solve(
            NodePool("default"), catalog_items,
            mixed_pods(np.random.default_rng(2), 20),
        )
        assert metrics.MESH_DISPATCHES.value(entry="fused") > before


class TestMeshSpec:
    def test_parse_specs(self):
        assert parse_mesh_spec(None) is None
        assert parse_mesh_spec("") is None
        assert parse_mesh_spec("0") is None
        assert parse_mesh_spec("off") is None
        m = parse_mesh_spec("8")
        assert m is not None and m.devices.size == 8
        m2 = parse_mesh_spec("2x4")
        assert m2 is not None and m2.devices.shape == (2, 4)

    def test_oversized_spec_fails_loudly(self):
        with pytest.raises(ValueError, match="devices"):
            parse_mesh_spec(str(len(jax.devices()) * 2))


@pytest.fixture()
def mesh_server():
    """A sidecar whose every device dispatch runs the sharded entries."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    srv = SolverServer(insecure_tcp=True, mesh=make_mesh(8)).start()
    yield srv
    srv.stop()


@pytest.fixture()
def mesh_client(mesh_server):
    c = SolverClient(
        mesh_server.address[0], mesh_server.address[1], delta=True,
        track_transport=False,
    )
    yield c
    c.close()


class TestMeshWire:
    """The sharded sidecar: wire == host == sharded, and the per-shard
    delta-epoch contracts (composition, pressure eviction, mid-flight
    StaleEpochError) behave exactly as on one device."""

    def test_wire_solve_matches_host(self, mesh_client, catalog_items):
        pool = NodePool("default")
        sd = TPUSolver(g_max=64, client=mesh_client, breaker=False)
        host = TPUSolver(g_max=64)
        rng = np.random.default_rng(21)
        for tick in range(3):
            pods = mixed_pods(rng, 50, salt=100 + tick)
            assert decision_sig(sd.solve(pool, catalog_items, list(pods))) == \
                decision_sig(host.solve(pool, catalog_items, list(pods)))

    def test_delta_epochs_compose_across_ticks(self, mesh_client, catalog_items):
        """Per-shard epochs compose: full ship, then row-wise deltas, all
        solved sharded, all bit-identical to an unsharded host solve."""
        pool = NodePool("default")
        sd = TPUSolver(g_max=64, client=mesh_client, breaker=False)
        host = TPUSolver(g_max=64)
        rng = np.random.default_rng(23)
        pods = mixed_pods(rng, 40, salt=200)
        sd.solve(pool, catalog_items, list(pods))
        # churn a suffix: the next ship is a delta against the epoch base
        pods2 = pods[:-5] + mixed_pods(rng, 5, salt=201)
        res = sd.solve(pool, catalog_items, list(pods2))
        assert mesh_client.last_delta["mode"] in ("delta", "full")
        assert decision_sig(res) == decision_sig(
            host.solve(pool, catalog_items, list(pods2))
        )

    def test_pressure_eviction_restages_not_errors(
        self, mesh_server, mesh_client, catalog_items
    ):
        """Eviction under HBM pressure stays a NON-ERROR mid-sequence:
        the epoch store empties, the next delta's unknown-epoch rung
        full-restages, and the decision matches host bit-exactly."""
        pool = NodePool("default")
        sd = TPUSolver(g_max=64, client=mesh_client, breaker=False)
        host = TPUSolver(g_max=64)
        rng = np.random.default_rng(29)
        pods = mixed_pods(rng, 40, salt=300)
        sd.solve(pool, catalog_items, list(pods))
        try:
            # simulate a device at 95% (threshold 10% free): the server's
            # staging LRUs shrink to their floor on the next staging pass
            obs_hbm.set_stats_provider(lambda: {
                "dev:0": {"bytes_in_use": 950, "bytes_limit": 1000,
                          "peak_bytes_in_use": 950},
            })
            with mesh_server._lock:
                mesh_server._evict_for_pressure_locked()
            assert len(mesh_server._epochs) <= 1
        finally:
            obs_hbm.set_stats_provider(None)
        before = metrics.DELTA_EPOCH_RESTAGES.value()
        pods2 = pods[:-4] + mixed_pods(rng, 4, salt=301)
        res = sd.solve(pool, catalog_items, list(pods2))
        assert decision_sig(res) == decision_sig(
            host.solve(pool, catalog_items, list(pods2))
        )
        assert metrics.DELTA_EPOCH_RESTAGES.value() >= before

    def test_midflight_stale_epoch_surfaces_then_recovers(
        self, mesh_server, mesh_client, catalog_items
    ):
        """The pipelined contract per shard: a mid-flight epoch loss
        surfaces as StaleEpochError on the claim, and the synchronous
        retry full-restages against the sharded staging."""
        solver = TPUSolver(g_max=64, client=mesh_client, breaker=False)
        entry = solver._catalog(catalog_items)
        classes = encode.group_pods(mixed_pods(np.random.default_rng(31), 30, salt=400))
        cs = encode.encode_classes(classes, entry.tensors, c_pad=32)
        h = mesh_client.begin_solve_compact(entry.seqnum, entry.tensors, cs, g_max=64)
        mesh_client.finish_solve_compact(h)
        assert mesh_client.last_delta["mode"] == "full"
        cs2 = encode.encode_classes(classes, entry.tensors, c_pad=32)
        cs2.count[0] += 1
        with mesh_server._lock:
            mesh_server._epochs.clear()
        h2 = mesh_client.begin_solve_compact(entry.seqnum, entry.tensors, cs2, g_max=64)
        assert mesh_client.last_delta["mode"] == "delta"
        with pytest.raises(StaleEpochError):
            mesh_client.finish_solve_compact(h2)
        dec = mesh_client.solve_classes_compact(entry.seqnum, entry.tensors, cs2, g_max=64)
        assert int(dec.n_open) >= 0
        assert mesh_client.last_delta["mode"] == "full"

    def test_sim_replay_mesh_backend_matches_golden(self):
        """sharded == unsharded via SIM REPLAY digests (the acceptance
        criterion's second leg): the `mesh` backend replays a committed
        corpus scenario with every solve sharded over the device mesh
        and must reproduce the pinned host golden digest bit-for-bit."""
        import json

        from karpenter_tpu.sim.replay import replay
        from karpenter_tpu.sim.trace import read_trace

        root = os.path.join(os.path.dirname(__file__), "golden", "scenarios")
        with open(os.path.join(root, "digests.json")) as f:
            golden = json.load(f)
        events = read_trace(os.path.join(root, "diurnal-small.jsonl"))
        res = replay(events, backend="mesh", seed=20260803)
        assert res.digest == golden["diurnal-small"]

    def test_debug_doc_reports_mesh(self, mesh_client, catalog_items):
        solver = TPUSolver(g_max=64, client=mesh_client, breaker=False)
        solver.solve(
            NodePool("default"), catalog_items,
            mixed_pods(np.random.default_rng(1), 10, salt=500),
        )
        info = mesh_client.debug_info()
        assert info["mesh"]["devices"] == 8
