"""Seeded registry-drift violations: a metric family, a failpoint site,
and an RPC feature flag that no docs table mentions. Tests load this
under a forged rel of solver/rpc.py so the feature-flag scan applies."""
from karpenter_tpu import failpoints, metrics

UNDOCUMENTED = metrics.REGISTRY.counter(
    "karpenter_lintfixture_never_documented_total", "not in docs/metrics.md"
)

# a PREFIX of a documented family (karpenter_journal_writes_total): the
# match must be backtick-exact, not substring, for this to fire
PREFIX_OF_DOCUMENTED = metrics.REGISTRY.counter(
    "karpenter_journal_writes", "prefix of a documented family"
)


def poke():
    failpoints.eval("lintfixture.site.never.documented")


def handshake():
    features = ["lintfixture-feature-never-documented"]
    features.append("lintfixture-appended-feature-never-documented")
    return features
