"""Seeded determinism violations: every determinism/* rule must fire on
this file (tests/test_analysis.py asserts the exact rule set). NOT
imported by anything -- the checkers parse it."""
import glob
import os
import random
import time
import time as _time
import uuid
from datetime import datetime
from datetime import datetime as dt
from random import choice

import numpy as np

_decoy_rng = None


def fresh_id():
    return str(uuid.uuid4())  # determinism/uuid4: no *_rng in scope


def seeded_arm_id():
    # determinism/uuid4: reads a *_rng stream but the call sits on the
    # SEEDED arm, not the unseeded fallback -- the loose-exemption trap
    if _decoy_rng is not None:
        return f"{_decoy_rng.getrandbits(8):x}-{uuid.uuid4().hex}"
    return "fixed"


def jitter():
    return random.random()  # determinism/random: process-global entropy


def np_draw():
    return np.random.randint(10)  # determinism/random: global numpy stream


def stamp():
    return time.time()  # determinism/wallclock: not a now()/_now() seam


def born():
    return datetime.now()  # determinism/wallclock


def aliased_stamp():
    return _time.time()  # determinism/wallclock: an alias cannot launder it


def aliased_born():
    return dt.now()  # determinism/wallclock: from-import alias


def aliased_pick(xs):
    return choice(xs)  # determinism/random: from-imported entropy draw


def listing(d):
    return [p for p in glob.glob(d)]  # determinism/iter-order: unsorted listing


def scan(d):
    for entry in os.listdir(d):  # determinism/iter-order: unsorted listing
        yield entry


def set_loop(items):
    for x in set(items):  # determinism/iter-order: PYTHONHASHSEED order
        return x
    return None


def set_comp(items):
    return [x for x in {i.strip() for i in items}]  # determinism/iter-order
