"""Mid-rung seam fixture, loaded FORGED under karpenter_tpu/solver/rpc.py:
SolverClient._roundtrip lets RuntimeError (a ladder class) escape, which
its may_raise declaration does not cover -> seam-undeclared-escape. The
other rpc seams are stubbed clean so exactly one seam rule fires."""


class SolverClient:
    def _conn(self):
        pass

    def _try_shm(self, sock):
        pass

    def _roundtrip(self, header, tensors=()):
        # seeded: a ladder-class escape the seam never declared
        raise RuntimeError("routed outside the breaker")

    def begin_solve_compact(self, *a, **k):
        pass

    def finish_solve_compact(self, handle):
        pass

    def _solve_op(self, *a, **k):
        pass

    def _disrupt_roundtrip(self, *a, **k):
        pass

    def stage_catalog(self, *a, **k):
        pass


class SolverServer:
    def _dispatch(self, sock, header, tensors):
        pass
