"""Seeded zero-copy violations. The checker's scope is the HOT_PATH
manifest (keyed by the REAL framing files), so tests load this source
under a forged rel of solver/rpc.py (top-level functions scanned) and
solver/shm.py (RingEndpoint methods scanned)."""


def _send_frame(sock, views):
    header = b"".join(views)  # zerocopy: joining copy
    sock.sendall(header)


def _recv_frame(sock, view):
    data = bytes(view[4:])  # zerocopy: bytes(buffer-slice) copies
    return data.tobytes() if hasattr(data, "tobytes") else data  # zerocopy: tobytes


def _recv_exact(sock, n):
    buf = bytearray(n)
    return bytes(n)  # ALLOWED: bytes(size) preallocates, no violation


class RingEndpoint:
    def sendmsg(self, buffers):
        flat = b"".join(buffers)  # zerocopy: joining copy in ring sendmsg
        return len(flat)

    def recv_into(self, view):
        chunk = view.tobytes()  # zerocopy: tobytes on the ring read path
        return len(chunk)

    def recv(self, n):
        # NOT in the manifest for RingEndpoint: the compat shim may copy
        return bytes(bytearray(n))
