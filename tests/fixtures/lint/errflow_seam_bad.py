"""Seam-rule fixture, loaded FORGED under karpenter_tpu/solver/service.py
(the LADDER_SEAMS scope keys off real file paths):

- TPUSolver._finish_remote leaks ConnectionError -> seam-ladder-escape
  (the terminal rung's must_handle contract).
- TPUSolver._probe_sidecar is missing entirely -> seam-missing.
"""


class TPUSolver:
    def _finish_remote(self, pending):
        # seeded: a wire failure escaping the terminal rung instead of
        # degrading to the in-process host solve
        raise ConnectionError("leaked past the ladder")
