"""Clean lock usage: consistent ordering, RLock reentrancy, init-time
writes -- the locks checker must stay quiet here."""
import threading

FIRST = threading.Lock()
SECOND = threading.Lock()
RE = threading.RLock()


def ordered_a():
    with FIRST:
        with SECOND:
            pass


def ordered_b():
    with FIRST:
        with SECOND:
            pass


def reentrant_outer():
    with RE:
        reentrant_inner()  # RLock self-edge is reentrancy, not deadlock


def reentrant_inner():
    with RE:
        pass


def explicit_same_order():
    # explicit acquire/release in the SAME order as ordered_a/b: still clean
    FIRST.acquire()
    try:
        with SECOND:
            pass
    finally:
        FIRST.release()


def try_acquire_out_of_order():
    with SECOND:
        # a try-acquire is the sanctioned out-of-order pattern: no edge
        FIRST.acquire(blocking=False)
        FIRST.release()


class Guarded:
    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0  # constructor writes are pre-publication
        self.low = 0
        self.high = 0

    def set(self, v):
        with self._lock:
            self.value = v

    def bump(self):
        with self._lock:
            self.value += 1

    def window(self, lo, hi):
        with self._lock:
            self.low, self.high = lo, hi  # tuple write, still under the lock
