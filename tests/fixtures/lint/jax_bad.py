"""Seeded jax compilation-discipline violations.

The jaxjit family scans rels under solver/ and parallel/, and the
jaxhost family keys off the DEVICE_HOT_PATH manifest, so tests load this
source under a forged rel of karpenter_tpu/solver/ffd.py (where
solve_dense_tuple / make_inputs_staged are manifest functions and
solve_dense_tuple is a SANCTIONED_FETCH site).
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np

_scale_table = {}  # module-level MUTABLE (lowercase): closure hazard


# jaxjit/unbounded-static: pod_count is not in the bucketing manifest --
# one compiled program per distinct pending-pod count
@functools.partial(jax.jit, static_argnames=("pod_count",))
def bad_static(x, *, pod_count):
    # jaxjit/closure-state: reads module-level mutable state
    bias = _scale_table.get("bias", 0.0)
    # jaxjit/traced-branch: Python branch on a traced value
    if x.sum() > 0:
        x = x + bias
    # jaxjit/weak-dtype: arange without an explicit dtype
    pad = jnp.arange(pod_count)
    return x, pad


# jaxjit/unbounded-static: static_argnums is positional
@functools.partial(jax.jit, static_argnums=(1,))
def bad_nums(x, k):
    return x * k


def _helper_branches(v):
    # reached transitively from bad_transitive with a traced argument:
    # the branch hazard must not hide in a module-local helper
    while v.max() > 1.0:
        v = v * 0.5
    return v


@jax.jit
def bad_transitive(x):
    return _helper_branches(x)


class Solver:
    def __init__(self):
        self.scale = 2.0

    @functools.partial(jax.jit, static_argnames=("g_max",))
    def bad_method(self, x, *, g_max):
        # jaxjit/closure-state: instance state inside a jitted body
        return x * self.scale


def solve_dense_tuple(inp):
    # ffd_solve is a registered jit entry name: its result is a live
    # device value until laundered through a sanctioned fetch
    out = ffd_solve(inp)
    # jaxhost/scalar-cast: int() directly on a live jit-entry result
    n = int(out.n_open)
    # jaxhost/item: synchronous scalar round-trip
    first = out.take.item()
    # jaxhost/block-until-ready: explicit barrier on the hot path
    jax.block_until_ready(out)
    return n, first


def make_inputs_staged(staged, classes):
    # jaxhost/np-on-device: make_inputs_staged is NOT a sanctioned fetch
    # site -- this conversion forces a synchronous device->host copy
    host = np.asarray(staged.cap)
    fetched = jax.device_get(staged.price)
    return host, fetched
