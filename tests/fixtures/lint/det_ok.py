"""The sanctioned counterparts of det_bad.py: zero determinism/*
violations (tests/test_analysis.py asserts the checker stays quiet --
the exemptions are contract, not accident)."""
import glob
import random
import time
import uuid

_name_rng = None


def seeded_stream(seed):
    return random.Random(f"fixture:{seed}")  # seeded construction: exempt


def generate_name(prefix):
    # the documented unseeded-fallback shape: uuid4 on the arm where the
    # *_rng stream is None (the production default)
    if _name_rng is not None:
        return f"{prefix}{_name_rng.getrandbits(32):08x}"
    return f"{prefix}{uuid.uuid4().hex[:8]}"


def generate_token():
    # the inverted spelling of the same fallback shape
    if _name_rng is None:
        return f"tk-{uuid.uuid4().hex}"
    return f"tk-{_name_rng.getrandbits(64):016x}"


def now():
    return time.time()  # the named clock seam


def duration(t0):
    return time.monotonic() - t0  # durations never feed decisions


def elapsed(t0):
    import time as _t

    return _t.perf_counter() - t0  # aliased duration clock: still exempt


def listing(d):
    # listing inside a sorted() argument: the sort erases readdir order
    return sorted(p for p in glob.glob(d) if p.endswith(".jsonl"))


def ordered(items):
    return sorted(set(items))  # set is order-erased by the sort
