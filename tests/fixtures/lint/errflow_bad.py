"""Seeded errflow violations -- every handler rule must fire here
(tests/test_analysis.py pins the exact rule set and counts). The seam
rules fire on the forged-path fixture (errflow_seam_bad.py) instead:
their scope keys off the real LADDER_SEAMS file paths."""


def step():
    raise ValueError("boom")


def cleanup():
    pass


def swallow_crash_bare():
    try:
        step()
    except:  # noqa: E722 -- seeded: a bare except can swallow OperatorCrashed
        cleanup()


def swallow_crash_base():
    try:
        step()
    except BaseException:  # seeded: no raise in the handler body
        cleanup()


def broad_silent():
    fallback = None
    try:
        step()
    except Exception:  # seeded: neither raises, converts, counts, nor logs
        fallback = 1
    return fallback


def finally_eats():
    try:
        step()
    finally:
        return 0  # noqa: B012 -- seeded: swallows any in-flight exception
