"""Seeded resource-lifecycle violations -- every reslife rule must fire
here (tests/test_analysis.py pins the exact rule set and counts)."""
import mmap
import os
import socket
import threading


def risky():
    pass


def unreleased():
    s = socket.socket()
    s.connect(("127.0.0.1", 1))  # seeded: used, never closed, never escapes


def leak_before_handoff(holder):
    s = socket.socket()
    s.connect(("127.0.0.1", 1))  # seeded: can raise with nothing closing s
    holder.sock = s


def leak_window_to_close():
    f = os.open("/tmp/reslife-fixture", 0)
    risky()  # seeded: raises past the fall-through-only close below
    os.close(f)


def unjoined():
    t = threading.Thread(target=print)
    t.start()  # seeded: non-daemon, never joined, never escapes


class PinsForever:
    def __init__(self):
        self._mm = mmap.mmap(-1, 4096)  # seeded: no method ever releases it
