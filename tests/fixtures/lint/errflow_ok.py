"""Sanctioned error-handling shapes -- the errflow rules must stay
quiet on every one of these."""

METRIC = None
log = None


def step():
    raise ValueError("boom")


def reraises_crash():
    try:
        step()
    except BaseException:
        raise  # a crash passes through: sanctioned


def converts():
    try:
        step()
    except Exception as e:
        raise RuntimeError(f"typed: {e}") from e


def counts_metric():
    try:
        step()
    except Exception:
        METRIC.inc(site="here")


def logs_it():
    try:
        step()
    except Exception as e:
        log.warning("step failed", error=str(e))


def typed_return():
    try:
        step()
    except Exception as e:
        return ValueError(str(e))  # the fan-out conversion shape
    return None


def narrow_is_fine():
    try:
        step()
    except (ValueError, KeyError):
        pass  # narrow handlers are not the broad-swallow rule's business


def loop_break_inside_finally():
    try:
        step()
    finally:
        for i in range(3):
            if i:
                break  # the loop lives inside the finally: swallows nothing
