"""Seeded lock-discipline violations: an A<->B order cycle (via one
direct nesting and one call-through edge), a second cycle built from
explicit acquire()/release() sections, a non-reentrant self-deadlock
reachable through a callee, and mixed-guard attribute writes (one plain,
one through tuple unpacking)."""
import threading

ALPHA = threading.Lock()
BETA = threading.Lock()
GAMMA = threading.Lock()
DELTA = threading.Lock()
EPSILON = threading.Lock()


def alpha_then_beta():
    with ALPHA:
        with BETA:  # edge ALPHA -> BETA (nested with)
            pass


def beta_then_alpha():
    with BETA:
        take_alpha()  # edge BETA -> ALPHA (call-through footprint)


def take_alpha():
    with ALPHA:
        pass


def delta_then_epsilon():
    DELTA.acquire()  # explicit acquire holds DELTA for the section
    try:
        with EPSILON:  # edge DELTA -> EPSILON
            pass
    finally:
        DELTA.release()


def epsilon_then_delta():
    with EPSILON:
        DELTA.acquire()  # edge EPSILON -> DELTA: the explicit-form cycle
        DELTA.release()


def outer():
    with GAMMA:
        inner()  # GAMMA is non-reentrant: self-deadlock through the callee


def inner():
    with GAMMA:
        pass


class Tally:
    """self.count guarded in inc() but written bare in reset(): the
    locks/mixed-guard shape (the scrape-vs-observe race, distilled).
    self.total takes its unlocked write through tuple unpacking."""

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self.total = 0.0

    def inc(self):
        with self._lock:
            self.count = self.count + 1
            self.total += 1.0

    def reset(self):
        self.count = 0

    def clear(self):
        self.count, self.total = 0, 0.0
