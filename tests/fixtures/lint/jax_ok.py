"""Sanctioned jax patterns the discipline checker must stay quiet on.

Loaded under a forged rel of karpenter_tpu/solver/ffd.py (same scope as
jax_bad.py): manifest statics, shape-derived Python branching, constants,
dtype-explicit creation, and the sanctioned fetch barrier.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np

_INF = np.float32(np.inf)  # ALL_CAPS module constant: sanctioned closure
_CT_SHIFT = 8


# statics drawn from the bucketing manifest: bounded by construction
@functools.partial(jax.jit, static_argnames=("g_max", "objective"))
def good_solve(x, *, g_max, objective="price"):
    Z = x.shape[-1]            # shape reads are trace-time Python ints
    if Z > _CT_SHIFT:          # branching on shapes/statics is fine
        raise ValueError("geometry")
    if objective == "price":   # static arg: two programs total
        x = x * 2.0
    slot = jnp.arange(g_max, dtype=jnp.int32)   # explicit dtype
    acc = jnp.zeros((g_max, Z), jnp.float32)    # positional dtype
    flags = jnp.ones((g_max,), bool)            # builtin dtype
    return jnp.where(x > 0, x, _INF), slot, acc, flags


def _helper_clean(v, lo):
    # traced args flow through lax/jnp ops only -- no Python branching
    return jnp.maximum(v, lo)


@jax.jit
def good_transitive(x):
    return _helper_clean(x, 0.0)


def solve_dense_tuple(inp, g_max):
    # THE sanctioned fetch barrier: async prefetch, one device_get, then
    # host-side scalar reads on the fetched numpy
    out = ffd_solve(inp, g_max=g_max)
    for leaf in out:
        leaf.copy_to_host_async()
    out = SolveOutputs(*jax.device_get(tuple(out)))
    return np.asarray(out.take), int(out.n_open)
