"""Sanctioned resource-lifecycle shapes -- reslife must stay quiet on
every one of these (they are the repo's real idioms)."""
import os
import socket
import threading


def with_statement():
    with socket.socket() as s:
        s.connect(("127.0.0.1", 1))


def try_finally():
    s = socket.socket()
    try:
        s.connect(("127.0.0.1", 1))
    finally:
        s.close()


def except_edge_then_handoff(holder):
    # the _conn shape: close on the error edge, re-raise, adopt on success
    s = socket.socket()
    try:
        s.settimeout(1.0)
        s.connect(("127.0.0.1", 1))
    except OSError:
        s.close()
        raise
    holder.sock = s


def wrap_continues_the_resource(ctx, holder):
    s = socket.create_connection(("127.0.0.1", 1))
    try:
        s = ctx.wrap_socket(s)  # rebind-through-call: same resource
    except OSError:
        s.close()
        raise
    holder.sock = s


def daemon_thread():
    t = threading.Thread(target=print, daemon=True)
    t.start()


def joined_thread():
    t = threading.Thread(target=print)
    t.start()
    t.join()


def immediate_handoff(registry):
    s = socket.socket()
    registry.adopt(s)  # ownership transfer with no risky window


class Lifecycled:
    def __init__(self):
        self._fd = os.open("/tmp/reslife-fixture", 0)
        self._sock = socket.socket()

    def close(self):
        os.close(self._fd)  # arg-style release
        self._sock.close()  # receiver-style release
