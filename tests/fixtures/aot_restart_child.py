"""Restart-drill child for tests/test_aot.py (TestRestartDrill): one
process per phase over a shared cache root.

    python tests/fixtures/aot_restart_child.py serialize <root>
    python tests/fixtures/aot_restart_child.py restart   <root>

``serialize`` solves cold with both cache layers enabled and runs the
AOT plan synchronously so the exec store holds the tier-0 executables.
``restart`` is a fresh interpreter arming from that store: its first
production tick must record ZERO compiles and ZERO traces under the jax
witness and decide identically. Prints one JSON line per phase."""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import jax

jax.config.update("jax_platforms", "cpu")


def build_catalog():
    from karpenter_tpu.apis import TPUNodeClass
    from karpenter_tpu.apis.nodeclass import SubnetStatus
    from karpenter_tpu.cache.unavailable_offerings import UnavailableOfferings
    from karpenter_tpu.kwok.cloud import FakeCloud
    from karpenter_tpu.providers.instancetype import gen_catalog
    from karpenter_tpu.providers.instancetype.offerings import OfferingsBuilder
    from karpenter_tpu.providers.instancetype.provider import InstanceTypeProvider
    from karpenter_tpu.providers.instancetype.types import Resolver
    from karpenter_tpu.providers.pricing import PricingProvider

    cloud = FakeCloud()
    prov = InstanceTypeProvider(
        cloud,
        Resolver(gen_catalog.REGION),
        OfferingsBuilder(
            PricingProvider(cloud, cloud, gen_catalog.REGION),
            UnavailableOfferings(),
            {z.name: z.zone_id for z in gen_catalog.ZONES},
        ),
        UnavailableOfferings(),
    )
    nc = TPUNodeClass("default")
    nc.status_subnets = [
        SubnetStatus(s.id, s.zone, s.zone_id) for s in cloud.describe_subnets()]
    return prov.list(nc)


def make_pods(n=60):
    from karpenter_tpu.apis import Pod
    from karpenter_tpu.scheduling import Resources

    shapes = [("1", 2), ("2", 4), ("4", 8), ("500m", 1)]
    return [
        Pod(f"p{i}", requests=Resources(
            {"cpu": shapes[i % 4][0], "memory": f"{shapes[i % 4][1]}Gi"}))
        for i in range(n)
    ]


def decisions_sig(result):
    return sorted(
        (sorted(it.name for it in g.instance_types),
         sorted(p.metadata.name for p in g.pods))
        for g in result.new_groups
    )


def main() -> int:
    phase, root = sys.argv[1], sys.argv[2]

    from karpenter_tpu.analysis import jax_witness
    from karpenter_tpu.apis import NodePool
    from karpenter_tpu.solver.service import TPUSolver
    from karpenter_tpu.utils import enable_jax_compilation_cache

    jax_witness.install()
    home = enable_jax_compilation_cache(root)
    assert home, "cache must enable for the drill"
    exec_dir = os.path.join(home, "exec")

    items = build_catalog()
    pods = make_pods()
    pool = NodePool("default")
    out = {"phase": phase}

    if phase == "serialize":
        solver = TPUSolver(g_max=64)
        pad_cell = []
        orig = solver._dispatch_bound

        def cap(inp, placed, *a, **kw):
            pad_cell.append(int(placed.shape[0]))
            return orig(inp, placed, *a, **kw)

        solver._dispatch_bound = cap
        try:
            result = solver.solve(pool, items, pods)
        finally:
            solver._dispatch_bound = orig
        mgr = solver.enable_aot(exec_dir, serialize=True, duty=1.0,
                                pads=(pad_cell[0],))
        mgr.run_plan(solver._catalog(items), throttle=False)
        out["serialized"] = mgr.store.stats()["artifacts"]
        out["decisions"] = decisions_sig(result)
    else:
        solver = TPUSolver(g_max=64)
        solver.enable_aot(exec_dir, serialize=False, duty=1.0)
        out["loaded"] = solver.describe_aot()["loaded"]
        st0 = jax_witness.stats()
        t0 = time.perf_counter()
        with jax_witness.hot("restart-drill-first-tick"):
            result = solver.solve(pool, items, pods)
        out["first_tick_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
        st1 = jax_witness.stats()
        out["first_tick_compiles"] = st1["compiles_total"] - st0["compiles_total"]
        out["first_tick_traces"] = st1["traces_total"] - st0["traces_total"]
        out["decisions"] = decisions_sig(result)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    rc = main()
    # XLA's C++ teardown can abort ("terminate called without an active
    # exception") after a deserialized executable has run; the result is
    # already on stdout, so skip interpreter teardown entirely.
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(rc)
