"""End-to-end provisioning slice on the kwok rig:
pending pods -> FFD simulation -> NodeClaim -> fake fleet launch -> node
registration -> pod binding. Mirrors the reference's integration-test shape
(pkg/cloudprovider/suite_test.go + test/suites/integration)."""
import pytest

from karpenter_tpu.apis import NodeClaim, NodePool, Node, Pod, TPUNodeClass, labels as wk
from karpenter_tpu.apis.pod import PodAffinityTerm, TopologySpreadConstraint
from karpenter_tpu.cache.ttl import FakeClock
from karpenter_tpu.operator import Operator
from karpenter_tpu.scheduling import Operator as Op, Requirement, Resources, Taint, Toleration
from karpenter_tpu.scheduling import resources as res


@pytest.fixture
def env():
    clock = FakeClock(start=10_000.0)
    op = Operator(clock=clock)
    op.cluster.create(TPUNodeClass("default"))
    op.cluster.create(NodePool("default"))
    return op


def make_pods(n, cpu="500m", memory="1Gi", prefix="pod", **kw):
    return [
        Pod(f"{prefix}-{i}", requests=Resources({"cpu": cpu, "memory": memory}), **kw)
        for i in range(n)
    ]


class TestE2EProvisioning:
    def test_single_pod_end_to_end(self, env):
        pod = make_pods(1)[0]
        env.cluster.create(pod)
        ticks = env.settle()
        assert not env.cluster.pending_pods(), "pod still pending"
        claims = env.cluster.list(NodeClaim)
        nodes = env.cluster.list(Node)
        assert len(claims) == 1 and len(nodes) == 1
        claim = claims[0]
        assert claim.launched() and claim.registered() and claim.initialized()
        assert claim.provider_id.startswith("tpu:///")
        assert pod.node_name == nodes[0].metadata.name
        # instance actually exists in the fake cloud with cluster tags
        insts = env.cloud.describe_instances()
        assert len(insts) == 1
        assert insts[0].tags["karpenter.sh/nodeclaim"] == claim.metadata.name

    def test_bin_packing_consolidates_small_pods(self, env):
        for p in make_pods(20, cpu="100m", memory="128Mi"):
            env.cluster.create(p)
        env.settle()
        assert not env.cluster.pending_pods()
        # 20 tiny pods must share very few nodes, not 20
        assert len(env.cluster.list(Node)) <= 2

    def test_big_pods_fan_out(self, env):
        for p in make_pods(4, cpu="3", memory="12Gi"):
            env.cluster.create(p)
        env.settle()
        assert not env.cluster.pending_pods()
        nodes = env.cluster.list(Node)
        for node in nodes:
            used = env.cluster.node_usage(node.metadata.name)
            assert used.fits(node.allocatable)

    def test_nodepool_requirements_respected(self, env):
        pool = env.cluster.get(NodePool, "default")
        pool.template.requirements = [
            Requirement(wk.ARCH_LABEL, Op.IN, ["arm64"]),
            Requirement(wk.CAPACITY_TYPE_LABEL, Op.IN, ["on-demand"]),
        ]
        env.cluster.update(pool)
        env.cluster.create(make_pods(1)[0])
        env.settle()
        node = env.cluster.list(Node)[0]
        assert node.metadata.labels[wk.ARCH_LABEL] == "arm64"
        assert node.metadata.labels[wk.CAPACITY_TYPE_LABEL] == "on-demand"

    def test_pod_node_selector_zone(self, env):
        zone = env.cloud.describe_zones()[1].name
        env.cluster.create(Pod("zonal", requests=Resources({"cpu": "1"}), node_selector={wk.ZONE_LABEL: zone}))
        env.settle()
        node = env.cluster.list(Node)[0]
        assert node.metadata.labels[wk.ZONE_LABEL] == zone

    def test_taint_requires_toleration(self, env):
        pool = env.cluster.get(NodePool, "default")
        pool.template.taints = [Taint("dedicated", value="team-a")]
        env.cluster.update(pool)
        intolerant = make_pods(1, prefix="intolerant")[0]
        tolerant = Pod("tolerant", requests=Resources({"cpu": "1"}),
                       tolerations=[Toleration(key="dedicated", value="team-a")])
        env.cluster.create(intolerant)
        env.cluster.create(tolerant)
        env.settle()
        assert intolerant.pending  # cannot schedule anywhere
        assert not tolerant.pending
        assert env.provisioner.last_result is not None

    def test_gpu_pod_gets_gpu_node(self, env):
        gpu_pod = Pod("gpu", requests=Resources({"cpu": "2", "memory": "4Gi", res.GPU: 1}),
                      tolerations=[Toleration(operator="Exists")])
        env.cluster.create(gpu_pod)
        env.settle()
        assert not env.cluster.pending_pods()
        gpu_node = env.cluster.get(Node, gpu_pod.node_name)
        assert gpu_node.metadata.labels[wk.LABEL_INSTANCE_CATEGORY] in ("g", "p")

    def test_plain_pod_avoids_exotic_provisioning(self, env):
        # exotic avoidance is a *provisioning* decision: a plain pod must not
        # cause a GPU/metal node to be created (binding to an existing
        # untainted GPU node would still be legal kube behavior)
        env.cluster.create(make_pods(1, prefix="plain")[0])
        env.settle()
        claims = env.cluster.list(NodeClaim)
        assert len(claims) == 1
        cat = claims[0].metadata.labels[wk.LABEL_INSTANCE_CATEGORY]
        assert cat not in ("g", "p", "acc")
        assert claims[0].metadata.labels[wk.LABEL_INSTANCE_SIZE] != "metal"

    def test_ice_reroutes_capacity(self, env):
        # Exhaust spot + od capacity for the cheapest types in one zone by
        # zeroing every pool, then confirm launches land in another zone.
        zones = [z.name for z in env.cloud.describe_zones()]
        dead_zone = zones[0]
        for t in env.cloud.describe_instance_types():
            env.cloud.set_capacity(t.name, dead_zone, "spot", 0)
            env.cloud.set_capacity(t.name, dead_zone, "on-demand", 0)
        for p in make_pods(3):
            env.cluster.create(p)
        env.settle(max_ticks=30)
        assert not env.cluster.pending_pods()
        for node in env.cluster.list(Node):
            assert node.metadata.labels[wk.ZONE_LABEL] != dead_zone

    def test_inflight_claims_prevent_double_provisioning(self, env):
        for p in make_pods(5, cpu="100m", memory="128Mi"):
            env.cluster.create(p)
        # two provisioner passes before any node registers
        env.nodeclass_controller.reconcile_all()
        env.provisioner.reconcile()
        claims_after_first = len(env.cluster.list(NodeClaim))
        env.provisioner.reconcile()
        assert len(env.cluster.list(NodeClaim)) == claims_after_first

    def test_nodepool_limits_cap_capacity(self, env):
        pool = env.cluster.get(NodePool, "default")
        pool.limits = Resources({"cpu": "2"})  # tiny: at most one small node
        env.cluster.update(pool)
        for p in make_pods(8, cpu="1500m", memory="1Gi"):
            env.cluster.create(p)
        env.settle()
        claims = env.cluster.list(NodeClaim)
        total_cpu = sum(c.capacity.get(res.CPU) for c in claims)
        assert total_cpu <= 2000.0 or len(claims) <= 1
        assert env.cluster.pending_pods()  # the rest stays pending


class TestTopologyAndAffinity:
    def test_zone_spread_hard(self, env):
        tsc = TopologySpreadConstraint(max_skew=1, topology_key=wk.ZONE_LABEL, label_selector={"app": "web"})
        for i in range(6):
            env.cluster.create(
                Pod(
                    f"web-{i}",
                    requests=Resources({"cpu": "3"}),  # forces one pod per node
                    labels={"app": "web"},
                    topology_spread=[tsc],
                )
            )
        env.settle()
        assert not env.cluster.pending_pods()
        zone_counts = {}
        for i in range(6):
            pod = env.cluster.get(Pod, f"web-{i}")
            zone = env.cluster.get(Node, pod.node_name).metadata.labels[wk.ZONE_LABEL]
            zone_counts[zone] = zone_counts.get(zone, 0) + 1
        assert max(zone_counts.values()) - min(zone_counts.values()) <= 1
        assert len(zone_counts) >= 3

    def test_preferred_anti_affinity_survives_bind_time(self, env):
        """The binder must not drift off an honored preference: the anchor
        lands in its pinned zone, and the replica with preferred zone
        anti-affinity must bind OUTSIDE that zone even when the anchor's
        node has room (kube-scheduler scores InterPodAffinity; first-fit
        would co-locate)."""
        anchor = Pod("anchor", requests=Resources({"cpu": "500m", "memory": "1Gi"}),
                     labels={"app": "spready"},
                     node_selector={wk.ZONE_LABEL: "us-central-1a"})
        repelled = Pod(
            "repelled", requests=Resources({"cpu": "250m", "memory": "512Mi"}),
            labels={"app": "spready"},
            preferred_affinity_terms=[
                (10, PodAffinityTerm(label_selector={"app": "spready"},
                                     topology_key=wk.ZONE_LABEL, anti=True))
            ],
        )
        env.cluster.create(anchor)
        env.cluster.create(repelled)
        env.settle()
        assert not env.cluster.pending_pods()
        za = env.cluster.get(Node, anchor.node_name).metadata.labels[wk.ZONE_LABEL]
        zr = env.cluster.get(Node, repelled.node_name).metadata.labels[wk.ZONE_LABEL]
        assert za == "us-central-1a"
        assert zr != za, "bind-time scoring must honor the anti preference"

    def test_soft_hostname_spread_scored_at_bind(self, env):
        """ScheduleAnyway hostname spread: the binder spreads replicas
        across nodes with headroom instead of first-fit stacking (the
        kube-scheduler scoring the stand-in must mirror)."""
        # two one-pod anchors force two nodes up front
        anchors = [
            Pod(f"anchor-{i}", requests=Resources({"cpu": "3"}), labels={"a": "x"})
            for i in range(2)
        ]
        for p in anchors:
            env.cluster.create(p)
        env.settle()
        assert len({env.cluster.get(Pod, p.metadata.name).node_name for p in anchors}) == 2
        tsc = TopologySpreadConstraint(
            max_skew=1, topology_key=wk.HOSTNAME_LABEL,
            label_selector={"app": "web"}, when_unsatisfiable="ScheduleAnyway",
        )
        for i in range(2):
            env.cluster.create(
                Pod(f"web-{i}", requests=Resources({"cpu": "100m"}),
                    labels={"app": "web"}, topology_spread=[tsc])
            )
        env.settle()
        nodes = {env.cluster.get(Pod, f"web-{i}").node_name for i in range(2)}
        assert len(nodes) == 2, "soft hostname spread must bias across nodes"

    def test_hostname_anti_affinity(self, env):
        term = PodAffinityTerm(label_selector={"app": "solo"}, topology_key=wk.HOSTNAME_LABEL, anti=True)
        for i in range(3):
            env.cluster.create(
                Pod(f"solo-{i}", requests=Resources({"cpu": "100m"}), labels={"app": "solo"}, affinity_terms=[term])
            )
        env.settle()
        assert not env.cluster.pending_pods()
        node_names = {env.cluster.get(Pod, f"solo-{i}").node_name for i in range(3)}
        assert len(node_names) == 3  # pairwise separation


class TestStandaloneNodeClaim:
    """Claims are a launch API, not just a provisioner artifact: a
    user-created NodeClaim (static capacity, no NodePool) launches,
    registers, and serves pods -- the core's nodeclaim lifecycle
    (controllers/nodeclaim_lifecycle.py)."""

    def _claim(self, name="static-0"):
        from karpenter_tpu.apis.nodepool import NodeClassRef

        return NodeClaim(
            name,
            requirements=[
                Requirement(wk.ARCH_LABEL, Op.IN, ["amd64"]),
                Requirement(wk.LABEL_INSTANCE_CATEGORY, Op.IN, ["c"]),
            ],
            node_class_ref=NodeClassRef(name="default"),
        )

    def test_standalone_claim_launches_and_serves_pods(self, env):
        env.tick()  # resolve the nodeclass first
        env.cluster.create(self._claim())
        for _ in range(10):  # settle() exits on no-pending-pods; tick past
            env.tick()       # the registration/initialization delays
            env.clock.step(5.0)
        claim = env.cluster.get(NodeClaim, "static-0")
        assert claim.launched() and claim.registered()
        nodes = env.cluster.list(Node)
        assert len(nodes) == 1 and nodes[0].metadata.labels[wk.ARCH_LABEL] == "amd64"
        # the static capacity serves a pending pod without provisioning more
        pod = make_pods(1)[0]
        env.cluster.create(pod)
        env.settle()
        assert pod.node_name == nodes[0].metadata.name
        assert len(env.cluster.list(Node)) == 1

    def test_unready_nodeclass_retries_with_event(self, env):
        # claim created BEFORE the nodeclass resolves: LaunchFailed event,
        # level-triggered retry succeeds once status lands
        env.cluster.create(self._claim("static-1"))
        env.provisioner.reconcile()  # no nodeclass status yet
        env.nodeclaim_lifecycle.reconcile_all()
        evs = env.recorder.with_reason("LaunchFailed")
        assert evs and evs[0].name == "static-1"
        for _ in range(6):
            env.tick()
            env.clock.step(5.0)
        assert env.cluster.get(NodeClaim, "static-1").launched()

    def test_standalone_claim_expires(self, env):
        env.tick()
        claim = self._claim("static-2")
        claim.expire_after = 600.0
        env.cluster.create(claim)
        for _ in range(10):
            env.tick()
            env.clock.step(5.0)
        assert env.cluster.get(NodeClaim, "static-2").registered()
        env.clock.step(700.0)
        decisions = env.disruption.reconcile()
        assert ("static-2", "Expired") in decisions

    def test_standalone_claim_drifts_on_nodeclass_change(self, env):
        """The lifecycle controller stamps the nodeclass static hash at
        launch, so static capacity drifts when the nodeclass changes --
        the same coverage pool-owned claims get."""
        env.tick()
        env.cluster.create(self._claim("static-3"))
        for _ in range(10):
            env.tick()
            env.clock.step(5.0)
        nc = env.cluster.get(TPUNodeClass, "default")
        nc.user_data = "#!/bin/bash\necho changed"
        env.cluster.update(nc)
        env.nodeclass_controller.reconcile_all()
        env.clock.step(6 * 60.0)
        decisions = env.disruption.reconcile()
        assert ("static-3", "Drifted") in decisions


class TestNodeClassLifecycle:
    def test_nodeclass_resolves_status(self, env):
        env.tick()
        nc = env.cluster.get(TPUNodeClass, "default")
        assert nc.ready()
        assert len(nc.status_subnets) == 4
        assert nc.status_security_groups and nc.status_security_groups[0].id == "sg-nodes"
        assert {i.id for i in nc.status_images} >= {"img-std-amd64", "img-std-arm64"}
        assert nc.status_instance_profile
        assert nc.metadata.annotations["karpenter.tpu/nodeclass-hash"] == nc.static_hash()

    def test_unready_nodeclass_blocks_launch(self, env):
        nc = env.cluster.get(TPUNodeClass, "default")
        from karpenter_tpu.apis.nodeclass import SelectorTerm

        # a selector matching nothing (an EMPTY list is now an admission
        # error, as on the reference CRD) -> SubnetsReady False
        nc.subnet_selector_terms = [SelectorTerm(tags={"no-such-tag": "true"})]
        env.cluster.update(nc)
        env.cluster.create(make_pods(1)[0])
        env.settle(max_ticks=3)
        assert env.cluster.pending_pods()
        assert not env.cluster.list(Node)

    def test_bootstrap_userdata_rendered(self, env):
        env.cluster.create(make_pods(1)[0])
        env.settle()
        lts = env.cloud.describe_launch_templates()
        assert lts
        ud = lts[0].user_data
        assert "--cluster kwok-cluster" in ud
        assert "--node-labels" in ud

    def test_node_death_unbinds_pods(self, env):
        env.cluster.create(make_pods(1)[0])
        env.settle()
        inst = env.cloud.describe_instances()[0]
        env.cloud.kill_instance(inst.id)
        env.lifecycle.step()
        assert env.cluster.pending_pods()  # pod back to pending
        assert not env.cluster.list(Node)


class TestNodeClassValidationDryRun:
    def test_bad_user_toml_fails_validation(self, env):
        from karpenter_tpu.apis.nodeclass import COND_VALIDATION_SUCCEEDED

        nc = env.cluster.get(TPUNodeClass, "default")
        nc.image_family = "Immutable"
        nc.user_data = "[settings\nbroken = "
        env.cluster.update(nc)
        env.tick()
        nc = env.cluster.get(TPUNodeClass, "default")
        assert nc.status_conditions.is_false(COND_VALIDATION_SUCCEEDED)
        cond = nc.status_conditions.get(COND_VALIDATION_SUCCEEDED)
        assert "does not render" in cond.message
        # a nodeclass failing validation blocks launches
        env.cluster.create(make_pods(1, prefix="blocked")[0])
        env.settle(max_ticks=3)
        assert env.cluster.pending_pods()

    def test_missing_user_profile_fails_validation(self, env):
        from karpenter_tpu.apis.nodeclass import COND_VALIDATION_SUCCEEDED

        nc = env.cluster.get(TPUNodeClass, "default")
        nc.role = ""
        nc.instance_profile = "no-such-profile"
        env.cluster.update(nc)
        env.tick()
        nc = env.cluster.get(TPUNodeClass, "default")
        assert nc.status_conditions.is_false(COND_VALIDATION_SUCCEEDED)

    def test_validation_result_cached_by_hash(self, env):
        from karpenter_tpu.apis.nodeclass import COND_VALIDATION_SUCCEEDED

        env.tick()
        calls_before = env.cloud.calls.get("get_instance_profile", 0)
        nc = env.cluster.get(TPUNodeClass, "default")
        nc.role = ""
        nc.instance_profile = "real-profile"
        env.cloud.create_instance_profile("real-profile", {})
        env.cluster.update(nc)
        env.tick()
        env.tick()
        env.tick()
        # the existence check ran once for the new hash, not per tick
        calls = env.cloud.calls.get("get_instance_profile", 0) - calls_before
        assert calls == 1, calls
        nc = env.cluster.get(TPUNodeClass, "default")
        assert nc.status_conditions.is_true(COND_VALIDATION_SUCCEEDED)


class TestFeatureGateFlag:
    def test_feature_gates_parse_and_apply(self):
        from karpenter_tpu.__main__ import build_operator
        import argparse

        args = argparse.Namespace(
            cluster_name="c", interruption_queue="", vm_memory_overhead_percent=0.075,
            reserved_nics=0, isolated_network=False, tpu_solver=False,
            feature_gates="SpotToSpotConsolidation=true,ReservedCapacity=false",
            identity="",
        )
        op = build_operator(args)
        assert op.options.feature_gates["SpotToSpotConsolidation"] is True
        assert op.options.feature_gates["ReservedCapacity"] is False
        # the disruption controller consumes the merged gates
        assert op.disruption.feature_gates["SpotToSpotConsolidation"] is True


class TestPodArrivalWake:
    """Event-driven tick trigger: a pod arrival wakes the run loop early
    and the burst accumulates behind the batching window (the reference's
    provisioning-side request batcher shape, pkg/batcher/batcher.go:84-160
    mapped per SURVEY.md section 2.4)."""

    def test_wake_on_pod_added_and_window_batches(self):
        import threading
        import time as _t

        from karpenter_tpu.operator import Operator
        from karpenter_tpu.operator.operator import Options

        op = Operator(options=Options(batch_idle_duration=0.02, batch_max_duration=0.2))
        op.watch_pods()
        # no pods: the wait honors the full (short) tick interval
        t0 = _t.monotonic()
        op.wait_for_work(0.05)
        assert _t.monotonic() - t0 >= 0.05

        # a burst arriving mid-wait wakes early, then the idle window
        # closes ~20ms after the last arrival instead of the 5s interval
        def burst():
            for i in range(5):
                op.cluster.create(Pod(f"w-{i}", requests=Resources({"cpu": "100m"})))
                _t.sleep(0.005)

        th = threading.Thread(target=burst)
        t0 = _t.monotonic()
        th.start()
        op.wait_for_work(5.0)
        elapsed = _t.monotonic() - t0
        th.join()
        assert elapsed < 1.0, f"wake took {elapsed:.3f}s; the 5s interval was not cut short"
        # every pod of the burst is pending for the ONE solve that follows
        assert len(op.cluster.pending_pods()) == 5

    def test_wait_without_watch_sleeps_interval(self):
        from karpenter_tpu.operator import Operator

        import time as _t

        op = Operator()
        t0 = _t.monotonic()
        op.wait_for_work(0.03)
        assert _t.monotonic() - t0 >= 0.03


class TestDaemonSetOverheadE2E:
    """The provisioner wires store DaemonSets into node sizing: a pod that
    exactly fills the biggest node becomes unschedulable once a daemonset
    must fit beside it."""

    def test_daemonset_reserves_capacity(self, env):
        from karpenter_tpu.apis import DaemonSet
        from karpenter_tpu.scheduling import Resources

        env.tick()  # resolve nodeclass status so the catalog is available
        items = env.cloud_provider.get_instance_types(env.cluster.get(NodePool, "default"))
        biggest = max(items, key=lambda it: it.allocatable().get(res.CPU))
        cpu_m = biggest.allocatable().get(res.CPU)
        whale = Pod("whale", requests=Resources.from_base_units({res.CPU: cpu_m - 100.0}))
        env.cluster.create(DaemonSet("cni", requests=Resources({"cpu": "500m"})))
        env.cluster.create(whale)
        env.settle(max_ticks=10)
        assert whale.pending, "daemonset reserve must make the whale unschedulable"
        env.cluster.delete(DaemonSet, "cni")
        env.settle(max_ticks=10)
        assert not whale.pending, "with the daemonset gone the whale fits again"


class TestBinderHints:
    """Round-5 binder fast path: the scheduling decision's pod->claim
    assignments are consumed as validated binding hints, and a re-decide
    onto in-flight virtual capacity must not destroy them (the
    'inflight/<claim>' pseudo-name regression made 50k binds quadratic)."""

    def test_hints_survive_inflight_redecide(self):
        from karpenter_tpu.apis import Node, Pod
        from karpenter_tpu.cache.ttl import FakeClock
        from karpenter_tpu.controllers.provisioner import INFLIGHT_PREFIX
        from karpenter_tpu.operator import Operator
        from karpenter_tpu.scheduling import Resources

        op = Operator(clock=FakeClock(100_000.0))
        op.cluster.create(TPUNodeClass("default"))
        op.cluster.create(NodePool("default"))
        op.tick()
        for i in range(6):
            op.cluster.create(Pod(f"w{i}", requests=Resources({"cpu": "500m", "memory": "1Gi"})))
        # tick 1: decide + launch; tick 2 (no clock step, so nodes are not
        # ready yet): the provisioner RE-decides the still-pending pods
        # onto in-flight virtual capacity
        op.tick()
        op.tick()
        hints = op.provisioner._assignment_hints
        assert hints, "decision hints must exist while pods are pending"
        assert op.binder._assignment_hints is hints, "binder must share the dict"
        assert not any(v.startswith(INFLIGHT_PREFIX) for v in hints.values()), (
            f"re-decide left unresolvable pseudo-node hints: {hints}"
        )
        # once nodes are ready, every pod binds to its HINTED node
        op.settle(max_ticks=20)
        assert not op.cluster.pending_pods()
        names = {n.metadata.name for n in op.cluster.list(Node)}
        for p in op.cluster.list(Pod):
            assert p.node_name in names
        assert not hints, "hints are consumed/purged after binding"
