"""Device performance observatory suite (karpenter_tpu/obs/).

Covers the four observatory layers and their contracts:

- flight-data recorder: bounded ring, per-tick records through the REAL
  operator sweep, records SURVIVING a full brownout rung-1->3 climb
  (rung 2 throttles trace sampling, never the black box), and the crash
  drill -- a `crash` failpoint leaves a readable JSONL black box with
  >= the last 100 ticks;
- HBM accounting: memory_stats polling into gauges, headroom, owner
  attribution (staged bytes by kind on both the in-process solver and
  the sidecar debug op), and memory-PRESSURE eviction of the staging
  LRUs ahead of their fixed capacity;
- per-jit-entry cost table: dispatch probes over JIT_ENTRY_FUNCTIONS,
  cache-size forwarding, witness-attributed compiles;
- on-demand profiler capture: tick bracketing writes a real trace dir,
  brownout throttling defers an armed capture;
- the /debug surface: the index enumerates every endpoint, the docs
  table stays in sync, and loopback-only enforcement holds across ALL
  debug endpoints (parametrized over the same index).
"""
import json
import os
import socket
import urllib.error
import urllib.request

import numpy as np
import pytest

from karpenter_tpu import metrics
from karpenter_tpu.apis import NodePool, Pod, TPUNodeClass
from karpenter_tpu.cache.ttl import FakeClock
from karpenter_tpu.obs import flight, hbm, jitstats
from karpenter_tpu.obs.profiler import PROFILER, ProfilerCapture
from karpenter_tpu.operator import Operator, Options
from karpenter_tpu.operator.health import DEBUG_ENDPOINTS, HealthServer
from karpenter_tpu.scheduling import Resources
from karpenter_tpu.solver.service import TPUSolver


@pytest.fixture()
def clean_obs():
    """Observatory globals cleared before AND after: the flight ring and
    the hbm provider are process-wide (by design, like the tracer), and
    state leaking across tests would make every assert order-dependent."""
    flight.RECORDER.clear()
    flight.RECORDER.configure(capacity=flight.CAPACITY_DEFAULT)
    hbm.set_stats_provider(None)
    PROFILER.reset()
    yield
    flight.RECORDER.clear()
    flight.RECORDER.configure(capacity=flight.CAPACITY_DEFAULT)
    hbm.set_stats_provider(None)
    PROFILER.reset()


@pytest.fixture(scope="module")
def catalog_items():
    from karpenter_tpu.apis.nodeclass import SubnetStatus
    from karpenter_tpu.cache.unavailable_offerings import UnavailableOfferings
    from karpenter_tpu.kwok.cloud import FakeCloud
    from karpenter_tpu.providers.instancetype import gen_catalog
    from karpenter_tpu.providers.instancetype.offerings import OfferingsBuilder
    from karpenter_tpu.providers.instancetype.provider import InstanceTypeProvider
    from karpenter_tpu.providers.instancetype.types import Resolver
    from karpenter_tpu.providers.pricing import PricingProvider

    cloud = FakeCloud()
    prov = InstanceTypeProvider(
        cloud,
        Resolver(gen_catalog.REGION),
        OfferingsBuilder(
            PricingProvider(cloud, cloud, gen_catalog.REGION),
            UnavailableOfferings(),
            {z.name: z.zone_id for z in cloud.describe_zones()},
        ),
        UnavailableOfferings(),
    )
    nc = TPUNodeClass("default")
    nc.status_subnets = [
        SubnetStatus(s.id, s.zone, s.zone_id) for s in cloud.describe_subnets()
    ]
    return prov.list(nc)


def _rig(solver=None, **opts):
    op = Operator(clock=FakeClock(1.0), solver=solver,
                  options=Options(tracing=True, tracing_sample=1.0, **opts))
    op.cluster.create(TPUNodeClass("default"))
    op.cluster.create(NodePool("default"))
    return op


def _fake_stats(in_use, limit=1000):
    return {"dev:0": {"bytes_in_use": in_use, "bytes_limit": limit,
                      "peak_bytes_in_use": in_use}}


# ---------------------------------------------------------------------------
# flight-data recorder


class TestFlightRecorder:
    def test_ring_bounded_and_seq_monotonic(self, clean_obs):
        rec = flight.FlightDataRecorder(capacity=8)
        for i in range(20):
            rec.record({"tick_ms": float(i)})
        d = rec.dump()
        assert d["ticks_recorded"] == 20
        assert len(d["records"]) == 8
        assert [r["seq"] for r in d["records"]] == list(range(13, 21))

    def test_operator_tick_records(self, clean_obs):
        op = _rig(solver=TPUSolver(g_max=64))
        for i in range(3):
            op.cluster.create(Pod(
                f"w{i}", requests=Resources({"cpu": "500m", "memory": "1Gi"})))
        op.settle(max_ticks=6)
        last = flight.RECORDER.last()
        assert last is not None
        assert last["tick_ms"] >= 0.0
        # tracing is on at sample 1.0: the span-tree stage summary lands
        assert "stages_ms" in last and "snapshot" in last["stages_ms"]
        # solver attribution fields ride along
        assert "staged_bytes" in last and last["staged_bytes"]["catalog"] > 0
        assert "dirty_fraction" in last
        assert last["nodes_ready"] == int(metrics.NODES_READY.value())

    def test_observatory_off_records_nothing(self, clean_obs):
        op = _rig(observatory=False)
        before = flight.RECORDER.dump()["ticks_recorded"]
        op.tick()
        assert flight.RECORDER.dump()["ticks_recorded"] == before

    def test_flush_blackbox_jsonl(self, clean_obs, tmp_path):
        rec = flight.FlightDataRecorder(capacity=4)
        assert rec.flush_blackbox("manual") is None, "empty ring never flushes"
        for i in range(6):
            rec.record({"tick_ms": float(i)})
        path = str(tmp_path / "box" / "flightdata.jsonl")
        assert rec.flush_blackbox("manual", path=path) == path
        lines = [json.loads(l) for l in open(path).read().splitlines()]
        assert lines[0]["flight_data"] == 1
        assert lines[0]["reason"] == "manual"
        assert lines[0]["records"] == 4
        assert [l["seq"] for l in lines[1:]] == [3, 4, 5, 6]
        assert not os.path.exists(path + ".tmp"), "write-then-rename"

    def test_stage_summary_from_span_tree(self, clean_obs):
        from karpenter_tpu import tracing

        tr = tracing.Tracer(enabled=True, sample=1.0, slow_ms=float("inf"))
        with tr.trace("tick", force=True) as root:
            with tr.span("provisioner"):
                with tr.span("snapshot"):
                    pass
                with tr.span("drain"):
                    tr.graft({
                        "trace": {"trace_id": "t", "span_id": "s"},
                        "spans": [{"name": "device", "start_ms": 0.0,
                                   "dur_ms": 25.0}],
                    })
            with tr.span("bind"):
                pass
        out = flight.stage_summary(root)
        assert set(out["stages_ms"]) >= {"snapshot", "drain", "bind", "device"}
        assert out["device_ms"] == pytest.approx(25.0, abs=0.1)
        # the no-op singleton (tracing disabled) summarizes to nothing
        assert flight.stage_summary(tracing.NOOP) == {}


class TestFlightSurvivesBrownout:
    def test_records_through_full_rung_climb(self, clean_obs):
        """The black-box contract: a rung-1 -> 3 brownout climb (rung 2
        sheds trace sampling) must not cost the flight recorder a single
        tick. Every sweep under a hopeless deadline lands one record,
        and the ring's seq advances exactly with the ticks."""
        op = _rig(tick_deadline=1e-6)  # every tick overruns by orders
        ticks = 0
        before = flight.RECORDER.dump()["ticks_recorded"]
        while op.brownout.level < 3 and ticks < 40:
            op.tick()
            ticks += 1
        assert op.brownout.level == 3, "ladder must reach shed-delta"
        assert op.brownout.sheds_tracing()
        # rung 2 throttled the profiler like tracing...
        assert PROFILER.describe()["throttled"] is True
        # ...but the flight recorder kept writing EVERY tick
        d = flight.RECORDER.dump()
        assert d["ticks_recorded"] - before == ticks
        levels = [r.get("brownout_level", 0) for r in d["records"]]
        assert 3 in levels and any(l < 3 for l in levels), (
            "records span the climb, not just the end state")

    def test_quality_fields_through_full_rung_climb(self, clean_obs):
        """Quality attribution is black-box cargo: once a solve has
        produced a document, every tick's flight record carries the gap
        and waste fields -- INCLUDING the records written at the deepest
        brownout rung (quality rides solve_finish, which brownout never
        sheds; rung 2 throttles trace sampling only)."""
        op = _rig(solver=TPUSolver(g_max=64), tick_deadline=1e-6)
        for i in range(4):
            op.cluster.create(Pod(
                f"q{i}", requests=Resources({"cpu": "500m", "memory": "1Gi"})))
        ticks = 0
        while op.brownout.level < 3 and ticks < 40:
            op.tick()
            ticks += 1
        assert op.brownout.level == 3, "ladder must reach shed-delta"
        d = flight.RECORDER.dump()
        with_q = [r for r in d["records"] if "quality" in r]
        assert with_q, "quality fields must land in the black box"
        last = with_q[-1]
        assert last["optimality_gap"] >= 1.0
        q = last["quality"]
        assert q["realized_per_h"] >= q["bound_per_h"] > 0.0
        for key in ("stranded_cpu_fraction", "stranded_memory_fraction",
                    "fragmentation_index"):
            assert 0.0 <= q[key] <= 1.0, (key, q)
        # the deepest-rung records still carry it
        rung3 = [r for r in d["records"] if r.get("brownout_level") == 3]
        assert rung3 and any("quality" in r for r in rung3), (
            "rung 3 must not shed quality attribution")

    def test_profiler_throttle_recovers_with_ladder(self, clean_obs):
        from karpenter_tpu import overload

        ctrl = overload.BrownoutController(deadline=1.0, dwell=0)
        overload.install_brownout(ctrl)
        try:
            for _ in range(4):
                ctrl.observe(10.0)  # climb
            assert ctrl.level >= 2 and PROFILER.describe()["throttled"]
            for _ in range(30):
                ctrl.observe(0.0)  # recover (EWMA must decay below exit)
            assert ctrl.level == 0
            assert not PROFILER.describe()["throttled"]
        finally:
            overload.install_brownout(None)


class TestCrashDrillBlackbox:
    def test_crash_leaves_readable_blackbox(self, clean_obs, failpoints,
                                            tmp_path, monkeypatch):
        """The acceptance drill: >=100 warm ticks, then a `crash`
        failpoint kills the sweep -- the OperatorCrashed path must leave
        a parseable JSONL black box holding >= the last 100 ticks, with
        the crashing tick recorded and marked."""
        from karpenter_tpu.failpoints import OperatorCrashed

        box = str(tmp_path / "flightdata.jsonl")
        monkeypatch.setenv(flight.BLACKBOX_ENV, box)
        op = _rig()
        for _ in range(105):
            op.tick()
        failpoints.arm_spec("crash.provisioner.dispatch=crash")
        op.cluster.create(Pod(
            "doomed", requests=Resources({"cpu": "100m", "memory": "128Mi"})))
        with pytest.raises(OperatorCrashed):
            op.tick()
        assert os.path.exists(box)
        lines = [json.loads(l) for l in open(box).read().splitlines()]
        header, records = lines[0], lines[1:]
        assert header["reason"] == "operator-crashed"
        assert len(records) >= 100
        assert records[-1]["crashed"] is True
        # seqs are contiguous: no tick went unrecorded on the way down
        seqs = [r["seq"] for r in records]
        assert seqs == list(range(seqs[0], seqs[0] + len(seqs)))

    def test_watchdog_crash_escalation_flushes(self, clean_obs, tmp_path,
                                               monkeypatch):
        """The watchdog's crash rung flushes from its OWN thread -- the
        guaranteed path when the wedged tick never reaches a bytecode
        boundary and the async raise cannot land."""
        from karpenter_tpu import overload

        box = str(tmp_path / "wd.jsonl")
        monkeypatch.setenv(flight.BLACKBOX_ENV, box)
        flight.RECORDER.record({"tick_ms": 1.0})
        clock = {"t": 0.0}
        wd = overload.StuckTickWatchdog(
            deadline=1.0, multiples=(0.1, 0.2, 0.3),
            clock=lambda: clock["t"])
        wd.tick_started()
        clock["t"] = 10.0
        assert wd.check_now() == "cancel"
        assert wd.check_now() == "breaker-open"
        # the crash rung targets THIS thread; neutralize the raise by
        # finishing the tick is wrong (it stands down) -- instead accept
        # the raise and verify the flush happened first
        from karpenter_tpu.failpoints import OperatorCrashed

        try:
            wd.check_now()
        except OperatorCrashed:
            pass
        assert os.path.exists(box)
        header = json.loads(open(box).readline())
        assert header["reason"] == "watchdog-crash"


# ---------------------------------------------------------------------------
# HBM accounting


class TestHbmAccounting:
    def test_poll_gauges_peak_headroom(self, clean_obs):
        hbm.set_stats_provider(lambda: _fake_stats(300))
        snap = hbm.poll(max_age_s=0.0)
        assert snap["devices"]["dev:0"]["bytes_in_use"] == 300
        assert snap["headroom_fraction"] == pytest.approx(0.7)
        assert hbm.HBM_IN_USE.value(device="dev:0") == 300.0
        assert hbm.HBM_LIMIT.value(device="dev:0") == 1000.0
        # the peak ledger is a high-water mark across polls (a provider
        # SWAP resets it -- new device world -- so one provider varies)
        level = {"v": 800}
        hbm.set_stats_provider(lambda: _fake_stats(level["v"]))
        hbm.poll(max_age_s=0.0)
        level["v"] = 100
        hbm.poll(max_age_s=0.0)
        assert hbm.peak_bytes_max() >= 800
        assert hbm.HBM_IN_USE.value(device="dev:0") == 100.0

    def test_rate_limit_reuses_snapshot(self, clean_obs):
        calls = {"n": 0}

        def provider():
            calls["n"] += 1
            return _fake_stats(10)

        hbm.set_stats_provider(provider)
        hbm.poll(max_age_s=0.0)
        for _ in range(50):
            hbm.poll(max_age_s=60.0)
        assert calls["n"] == 1, "recent polls must reuse the snapshot"

    def test_no_ledger_means_no_pressure(self, clean_obs):
        hbm.set_stats_provider(lambda: None)  # the CPU-backend shape
        assert hbm.poll(max_age_s=0.0)["headroom_fraction"] is None
        assert hbm.headroom() is None
        assert not hbm.under_pressure()

    def test_under_pressure_threshold(self, clean_obs, monkeypatch):
        hbm.set_stats_provider(lambda: _fake_stats(950))  # 5% free
        assert hbm.under_pressure()
        hbm.set_stats_provider(lambda: _fake_stats(500))  # 50% free
        assert not hbm.under_pressure()
        monkeypatch.setenv(hbm.EVICT_HEADROOM_ENV, "0.6")
        assert hbm.under_pressure()
        monkeypatch.setenv(hbm.EVICT_HEADROOM_ENV, "0")
        assert not hbm.under_pressure(), "0 disables pressure eviction"

    def test_sum_nbytes_walks_structures(self):
        a = np.zeros(10, dtype=np.float32)   # 40 bytes
        b = np.zeros(4, dtype=np.int64)      # 32 bytes
        assert hbm.sum_nbytes(a) == 40
        assert hbm.sum_nbytes([a, b]) == 72
        assert hbm.sum_nbytes({"x": a, "y": (b, b)}) == 104
        assert hbm.sum_nbytes(None) == 0
        assert hbm.sum_nbytes(object()) == 0


class TestPressureEviction:
    def test_local_catalog_lru_shrinks_under_pressure(self, clean_obs,
                                                      catalog_items):
        s = TPUSolver(g_max=64)
        # three distinct catalog lists -> three LRU entries
        lists = [list(catalog_items) for _ in range(3)]
        for lst in lists:
            s.catalog_tensors(lst)
        assert len(s._catalog_cache) == 3
        before = metrics.SOLVER_STAGED_PRESSURE_EVICTIONS.value(kind="catalog")
        hbm.set_stats_provider(lambda: _fake_stats(990))  # 1% free
        fourth = list(catalog_items)
        s.catalog_tensors(fourth)
        assert len(s._catalog_cache) == 1, "pressure shrinks to the floor"
        # the survivor is the entry just staged
        assert next(iter(s._catalog_cache.values())).catalog_list is fourth
        assert metrics.SOLVER_STAGED_PRESSURE_EVICTIONS.value(
            kind="catalog") == before + 3

    def test_sidecar_staging_bytes_and_pressure(self, clean_obs,
                                                catalog_items):
        from karpenter_tpu.solver.rpc import SolverClient, SolverServer

        srv = SolverServer(insecure_tcp=True).start()
        clients = []
        try:
            pool = NodePool("default")
            pods = [Pod(f"p{i}", requests=Resources(
                {"cpu": "250m", "memory": "512Mi"})) for i in range(6)]
            # two solvers with distinct catalog lists -> two staged seqnums
            for _ in range(2):
                c = SolverClient(srv.address[0], srv.address[1])
                clients.append(c)
                TPUSolver(g_max=64, client=c).solve(
                    pool, list(catalog_items), pods)
            dbg = clients[0].debug_info()
            assert len(dbg["staged_seqnums"]) == 2
            assert dbg["staged_bytes"]["catalog"] > 0
            assert metrics.SOLVER_STAGED_BYTES.value(kind="catalog") > 0
            # pressure: the next stage op shrinks the LRU to its floor
            hbm.set_stats_provider(lambda: _fake_stats(995))
            c = SolverClient(srv.address[0], srv.address[1])
            clients.append(c)
            TPUSolver(g_max=64, client=c).solve(
                pool, list(catalog_items), pods)
            dbg = clients[0].debug_info()
            assert len(dbg["staged_seqnums"]) == 1
            assert metrics.SOLVER_STAGED_PRESSURE_EVICTIONS.value(
                kind="catalog") >= 2
        finally:
            for c in clients:
                c.close()
            srv.stop()

    def test_staged_bytes_by_kind_local(self, clean_obs, catalog_items):
        s = TPUSolver(g_max=64)
        pool = NodePool("default")
        pods = [Pod(f"b{i}", requests=Resources(
            {"cpu": "250m", "memory": "512Mi"})) for i in range(4)]
        s.solve(pool, list(catalog_items), pods)
        by_kind = s.staged_bytes_by_kind()
        assert by_kind["catalog"] > 0
        assert by_kind["solve_temporaries"] > 0
        assert metrics.SOLVER_STAGED_BYTES.value(kind="catalog") == float(
            by_kind["catalog"])
        doc = s.describe_wire()
        assert doc["staged_bytes"] == by_kind


# ---------------------------------------------------------------------------
# per-jit-entry cost table


class TestJitStats:
    def test_dispatch_probes_account_and_forward(self, clean_obs,
                                                 catalog_items):
        from karpenter_tpu.analysis import jax_witness
        from karpenter_tpu.solver import ffd

        was_installed = jitstats.installed()
        jitstats.install()
        jitstats.reset()
        try:
            assert getattr(ffd.ffd_solve_fused, "_karpenter_jit_probe", False)
            # cache-size introspection keeps working through the probe
            sizes = jax_witness.entry_cache_sizes()
            assert "karpenter_tpu.solver.ffd.ffd_solve_fused" in sizes
            s = TPUSolver(g_max=64)
            pods = [Pod(f"j{i}", requests=Resources(
                {"cpu": "250m", "memory": "512Mi"})) for i in range(4)]
            s.solve(NodePool("default"), list(catalog_items), pods)
            table = jitstats.table()
            fused = table["karpenter_tpu.solver.ffd.ffd_solve_fused"]
            assert fused["dispatches"] >= 1
            assert fused["dispatch_ms"] > 0.0
            assert "cache_size" in fused
            assert jitstats.JIT_DISPATCHES.value(
                entry="karpenter_tpu.solver.ffd.ffd_solve_fused") >= 1
        finally:
            if not was_installed:
                jitstats.uninstall()

    def test_install_idempotent_uninstall_restores(self, clean_obs):
        import sys

        from karpenter_tpu.solver import ffd

        was_installed = jitstats.installed()
        if was_installed:
            jitstats.uninstall()
        orig = ffd.ffd_solve_fused
        try:
            assert jitstats.install() > 0
            assert jitstats.install() == 0, "second install wraps nothing"
            assert ffd.ffd_solve_fused is not orig
            jitstats.uninstall()
            assert ffd.ffd_solve_fused is orig
        finally:
            if was_installed:
                jitstats.install()

    def test_witness_attributes_compiles_to_entry(self, clean_obs,
                                                  catalog_items):
        """The compile listener runs synchronously in the dispatching
        thread, so a traces_total delta across one probe call belongs to
        that entry: a fresh g_max forces a retrace and the table blames
        the right program."""
        from karpenter_tpu.analysis import jax_witness

        jax_witness.install()
        was_installed = jitstats.installed()
        jitstats.install()
        jitstats.reset()
        try:
            pods = [Pod(f"c{i}", requests=Resources(
                {"cpu": "250m", "memory": "512Mi"})) for i in range(3)]
            # an unusual g_max: a cold jit cache key -> at least one trace
            TPUSolver(g_max=39).solve(
                NodePool("default"), list(catalog_items), pods)
            table = jitstats.table()
            compiled = [e for e, row in table.items() if row["compiles"] > 0]
            assert compiled, f"no entry attributed a compile: {table}"
            assert all(e.startswith("karpenter_tpu.solver.") for e in compiled)
        finally:
            if not was_installed:
                jitstats.uninstall()


# ---------------------------------------------------------------------------
# profiler capture


class TestProfilerCapture:
    def test_capture_brackets_ticks_and_writes_trace(self, clean_obs,
                                                     tmp_path):
        cap = ProfilerCapture()
        out = str(tmp_path / "prof")
        cap.request(2, out_dir=out)
        assert cap.describe()["armed_ticks"] == 2
        import jax.numpy as jnp

        for _ in range(2):
            cap.on_tick_start()
            (jnp.arange(16.0) * 2).sum().block_until_ready()
            cap.on_tick_end()
        d = cap.describe()
        assert d["armed_ticks"] == 0 and not d["active"]
        assert cap.captures == 1
        trace_dir = d["last_trace_dir"]
        assert trace_dir and os.path.isdir(trace_dir)
        assert any(files for _, _, files in os.walk(trace_dir)), (
            "the capture must leave real trace files for tensorboard/xprof")

    def test_throttled_capture_defers_then_resumes(self, clean_obs, tmp_path):
        cap = ProfilerCapture()
        cap.request(1, out_dir=str(tmp_path / "p2"))
        cap.set_throttled(True)
        cap.on_tick_start()
        assert not cap.describe()["active"], "brownout rung 2 defers capture"
        cap.on_tick_end()
        assert cap.describe()["armed_ticks"] == 1, "armed ticks survive"
        cap.set_throttled(False)
        cap.on_tick_start()
        assert cap.describe()["active"]
        cap.on_tick_end()
        assert cap.captures == 1

    def test_idle_bracket_is_noop(self, clean_obs):
        cap = ProfilerCapture()
        cap.on_tick_start()
        cap.on_tick_end()
        assert cap.describe() == {
            "armed_ticks": 0, "active": False, "throttled": False,
            "out_dir": None, "captures": 0, "errors": 0,
            "last_trace_dir": None,
        }


# ---------------------------------------------------------------------------
# the /debug surface


def _nonloopback_ip():
    """A local address whose connections arrive with a non-loopback
    source, or None (loopback-only hosts skip the 403 leg)."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.255", 1))
            ip = s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return None
    return None if ip.startswith("127.") else ip


class TestDebugSurface:
    @pytest.fixture()
    def srv(self, clean_obs):
        server = HealthServer(port=0).start()
        yield server
        server.stop()

    def test_index_enumerates_every_endpoint(self, srv):
        doc = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/debug/", timeout=10).read())
        assert doc["endpoints"] == DEBUG_ENDPOINTS
        # the bare spelling serves the same index
        doc2 = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/debug", timeout=10).read())
        assert doc2 == doc

    def test_docs_table_matches_index(self):
        """docs/observability.md must document every debug endpoint the
        index serves -- the registry-drift discipline, applied to the
        debug surface."""
        doc = open(os.path.join(
            os.path.dirname(__file__), "..", "docs", "observability.md")
        ).read()
        for path in DEBUG_ENDPOINTS:
            assert f"`{path}`" in doc, f"docs/observability.md missing {path}"

    @pytest.mark.parametrize("endpoint", sorted(DEBUG_ENDPOINTS))
    def test_endpoint_serves_on_loopback(self, srv, endpoint):
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}{endpoint}", timeout=10).read()
        assert body  # 200 with a body, configured or not

    @pytest.mark.parametrize("endpoint",
                             sorted(DEBUG_ENDPOINTS) + ["/debug/",
                                                        "/debug/profile?ticks=3"])
    def test_endpoint_rejects_non_loopback(self, srv, endpoint):
        """THE enforcement contract, across the whole surface including
        the index and the profile-arming form: a non-loopback peer gets
        403 and nothing else happens."""
        ip = _nonloopback_ip()
        if ip is None:
            pytest.skip("no non-loopback interface on this host")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                f"http://{ip}:{srv.port}{endpoint}", timeout=10)
        assert exc.value.code == 403
        # the arming form must not have armed anything
        assert PROFILER.describe()["armed_ticks"] == 0

    def test_flightdata_endpoint_serves_ring(self, srv):
        flight.RECORDER.record({"tick_ms": 7.0})
        doc = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/debug/flightdata", timeout=10).read())
        assert doc["records"][-1]["tick_ms"] == 7.0
        assert doc["capacity"] == flight.CAPACITY_DEFAULT

    def test_quality_endpoint_serves_last_document(self, srv):
        """Unconfigured before any solve; the live quality document
        after one (the same process-wide store solve_finish writes)."""
        from karpenter_tpu.obs import quality

        quality.reset()
        doc = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/debug/quality", timeout=10).read())
        assert doc == {"configured": False}
        quality.record({"optimality_gap": 1.25, "realized_per_h": 5.0})
        try:
            doc = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/quality",
                timeout=10).read())
            assert doc["optimality_gap"] == 1.25
        finally:
            quality.reset()

    def test_profile_endpoint_unconfigured_when_observatory_off(self, srv):
        """With the observatory off no tick would ever service a
        capture: the endpoint must report unconfigured and never arm."""
        srv.profile_enabled = False
        doc = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/debug/profile?ticks=5",
            timeout=10).read())
        assert doc == {"configured": False}
        assert PROFILER.describe()["armed_ticks"] == 0

    def test_profile_endpoint_arms_and_describes(self, srv):
        doc = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/debug/profile?ticks=5",
            timeout=10).read())
        assert doc["armed_ticks"] == 5
        doc = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/debug/profile", timeout=10).read())
        assert doc["armed_ticks"] == 5, "bare GET reads state, arms nothing"
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/profile?ticks=bogus",
                timeout=10)
        assert exc.value.code == 400


# ---------------------------------------------------------------------------
# overhead: the bench helper's direct-cost measurement stays tiny


class TestObservatoryOverhead:
    def test_per_tick_cost_is_microscopic(self, clean_obs, catalog_items):
        """The bench stage asserts <1% of the tier's tick; here the
        absolute per-tick observatory cost is bounded so a regression
        (an accidental O(pods) walk, an unthrottled poll) fails tier-1
        without needing the bench."""
        import bench

        s = TPUSolver(g_max=64)
        pods = [Pod(f"o{i}", requests=Resources(
            {"cpu": "250m", "memory": "512Mi"})) for i in range(4)]
        s.solve(NodePool("default"), list(catalog_items), pods)
        out = bench._observatory_overhead(s, off_p50_ms=100.0)
        assert out["observatory_tick_cost_ms"] < 2.0, out
        assert out["observatory_overhead_ok"] is not None

    def test_observatory_fields_shape(self, clean_obs, catalog_items):
        import bench

        s = TPUSolver(g_max=64)
        s.catalog_tensors(list(catalog_items))
        hbm.set_stats_provider(lambda: _fake_stats(400))
        out = bench._observatory_fields(s)
        assert out["device_hbm_peak_bytes"] >= 400
        assert out["staged_bytes_by_kind"]["catalog"] > 0
