"""Scale benchmark: the BASELINE.json north-star measurement.

Measures END-TO-END scheduling-decision latency for 50k pending pods against
the full instance-type catalog: real Pod objects in, NewNodeGroup decisions
out. The measured path is exactly the Provisioner's
(controllers/provisioner.py -> solver/service.TPUSolver.solve):

    host   group_pods          pod objects -> equivalence classes (interned
                               per-pod signatures; the grouping cache)
    host   encode_classes      classes -> dense tensors
    device batched FFD         masks + packed-bitset compat + scan
    host   _decode             placements -> NewNodeGroups w/ offerings

The HEADLINE metric is COLD-PODS (VERDICT round 2, weak #2): every measured
iteration sees fresh Pod objects whose grouping signatures have never been
computed, the shape of a steady-state tick where pending pods arrive from
watch events. Pods of one workload template share one spec object, as
ReplicaSet replicas do. Warm-iteration latency (the same pending set
re-solved, e.g. an unsatisfiable remainder re-examined every tick) is
reported as a secondary field.

Target (BASELINE.md): < 100 ms p99 @ 50k pods x ~700 types.
The reference has no published number for this path -- its in-process Go FFD
is the implicit baseline and the 100 ms target is the contract; vs_baseline
reports target/measured (>1 means beating the target).

The packing objective is price-aware (BASELINE.json configs 3-4,
solver/ffd.py objective == "price"): groups open on the min total-class-cost
type inside a density envelope. A max-fit ("fit" objective) solve of the
same workload is run once for the A/B fleet-price comparison
(fleet_price_fit_mode in the JSON).

Robustness contract (VERDICT rounds 1-3): this script NEVER exits non-zero
and ALWAYS prints exactly one JSON line on stdout, and a mid-run tunnel
loss must surface the best completed ACCELERATOR partial, not silently
degrade the whole run to CPU. Structure:

  parent process   probe (subprocess, growing timeouts, wall budget)
                   -> spawn the measurement CHILD, watch its progress file
                   -> stall/timeout: kill child, assemble a partial result
                      from the completed iterations ("partial": true)
                   -> nothing usable: re-run the child forced-CPU
                      ("degraded": true) and attach the committed TPU
                      capture (BENCH_TPU_CAPTURE.json) as claim provenance
  child process    the actual measurement; emits one JSONL event per
                   phase/iteration (cold pass FIRST -- the headline must
                   land before anything else can be lost)

The parent never imports jax, so no tunnel state can hang it. Every knob is
env-tunable: BENCH_PROBE_TIMEOUT_S/ATTEMPTS/BUDGET_S, BENCH_BUDGET_S,
BENCH_STALL_S, BENCH_CPU_BUDGET_S.

Tail instrumentation (VERDICT round 3, item 2): per-iteration wall time and
gen2-GC deltas for BOTH passes land in the JSON (cold_iters_ms /
warm_iters_ms / gc_gen2_during_measurement), plus tunnel RTT sampled before
and after the cold pass (rtt jitter vs compute jitter separation).

Production sustained-tick measurement (round 6, a HEADLINE field):
`production_tick_ms` -- K back-to-back cold ticks through the exact
solve_begin/solve_finish halves the provisioner's double-buffered tick
runs by default, the result fetch of tick i overlapping tick i+1's host
stages: a MEASURED end-to-end per-tick wall with no tunnel term to
subtract, on the path production actually executes.

Secondary measurements (round 5, each fenced so it can never cost the
headline): `rpc_loopback_p50_ms` -- the tick through the production
sidecar topology (solver/rpc.py over a local UNIX socket, itself now
request-pipelined); `mixed_affinity_*` -- the tick with ~1% affinity pods
riding the oracle suffix (solver/service.py round-5 carve);
`trace_stages_ms` / `overlap_fraction_p50` -- per-stage span p50/p99
(snapshot, encode, wire, device, decode, bind, ...) and the pipeline
overlap fraction from a traced run of the production rig topology
(karpenter_tpu/tracing.py); `tracing_overhead_pct` -- the measured
tracing tax (contract: <2%); `observatory_overhead_pct` -- the measured
device-observatory tax (karpenter_tpu/obs/: flight record + HBM poll +
staged-bytes attribution per tick; contract: <1%, the
observatory_overhead_ok boolean). BENCH_SKIP_SECONDARY=1 disables the
secondaries.

Wall-budget discipline (round 6): every stage budget -- probe, the
accelerator child, the CPU-fallback child -- clamps to what is left of
`BENCH_WALL_BUDGET_S` (default 3300 s, chosen to land the JSON line well
inside any sane driver timeout; round 5's artifact was lost to a probe
whose own 2 h budget exceeded the driver's, so the driver SIGKILLed the
process before the always-print-one-line contract could fire). A SIGTERM
handler is the last line of defense: it assembles the best partial from
the progress events and prints the one JSON line before exiting 0.

Usage: python bench.py            (one JSON line on stdout)
       python bench.py --profile  (extra breakdown on stderr)
       python bench.py --cpu      (skip the probe, force host CPU)
"""
from __future__ import annotations

import json
import os
import sys
import time
import traceback

import numpy as np

def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


def _env_i(name: str, default: int) -> int:
    return int(_env_f(name, default))


# scale knobs env-overridable for harness smoke tests ONLY; the driver's
# artifact always runs the 50k-pod defaults
N_PODS = _env_i("BENCH_N_PODS", 50_000)
N_SPEC_TEMPLATES = _env_i("BENCH_TEMPLATES", 160)
ITERS = _env_i("BENCH_ITERS", 60)          # warm iterations
COLD_ITERS = _env_i("BENCH_COLD_ITERS", 25)  # cold iterations (the headline)
WARMUP = 5
G_MAX = 1024        # price objective opens ~1.6x max-fit's group count
TARGET_MS = 100.0

CAPTURE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_TPU_CAPTURE.json")


def probe_backend(**kw):
    """Subprocess backend probe (shared with the operator entry point --
    karpenter_tpu.utils.probe_jax_backend, whose defaults this forwards):
    a hung device tunnel must not hang the benchmark; round 1 lost its
    number to exactly that."""
    from karpenter_tpu.utils import probe_jax_backend

    return probe_jax_backend(**kw)


def build_catalog_items():
    from karpenter_tpu.apis import TPUNodeClass
    from karpenter_tpu.apis.nodeclass import SubnetStatus
    from karpenter_tpu.cache.unavailable_offerings import UnavailableOfferings
    from karpenter_tpu.kwok.cloud import FakeCloud
    from karpenter_tpu.providers.instancetype import gen_catalog
    from karpenter_tpu.providers.instancetype.offerings import OfferingsBuilder
    from karpenter_tpu.providers.instancetype.provider import InstanceTypeProvider
    from karpenter_tpu.providers.instancetype.types import Resolver
    from karpenter_tpu.providers.pricing import PricingProvider

    cloud = FakeCloud()
    prov = InstanceTypeProvider(
        cloud,
        Resolver(gen_catalog.REGION),
        OfferingsBuilder(
            PricingProvider(cloud, cloud, gen_catalog.REGION),
            UnavailableOfferings(),
            {z.name: z.zone_id for z in cloud.describe_zones()},
        ),
        UnavailableOfferings(),
    )
    nc = TPUNodeClass("default")
    nc.status_subnets = [SubnetStatus(s.id, s.zone, s.zone_id) for s in cloud.describe_subnets()]
    return prov.list(nc), cloud


def synth_pods(rng: np.random.Generator, zones, n_pods: int, salt: int,
               templates: int = 0):
    """A 50k-pod pending set of REAL Pod objects (VERDICT round 1, item 2:
    host-side encoding must be inside the measurement). Spec mix modeled on
    the reference's scale-test workloads (test/suites/scale): many replicas
    over ~160 distinct deployment specs -- mostly small web pods, some
    medium services, a few large; ~20% zone-pinned, ~15% on-demand-only,
    some arch/category constrained, some tolerating dedicated taints.
    `templates` overrides the template-universe size (the warm-delta stage
    models arrival waves spanning a few dozen deployments, not all 160)."""
    from karpenter_tpu.apis import Pod, labels as wk
    from karpenter_tpu.scheduling import Resources, Toleration
    from karpenter_tpu.scheduling import resources as res

    cpu_choices = np.array([100, 100, 250, 250, 500, 500, 1000, 2000, 4000, 8000])
    mem_choices = np.array([128, 256, 512, 512, 1024, 2048, 4096, 8192, 16384, 32768])

    T = templates or N_SPEC_TEMPLATES
    sizes = rng.integers(0, len(cpu_choices), size=T)
    weights = rng.dirichlet(np.ones(T) * 0.5)
    counts = np.maximum(1, (weights * n_pods).astype(np.int64))
    counts[0] += n_pods - counts.sum()

    templates = []
    for t in range(T):
        selector = {}
        u = rng.random()
        if u < 0.20:
            selector[wk.ZONE_LABEL] = str(zones[int(rng.integers(0, len(zones)))])
        elif u < 0.35:
            selector[wk.CAPACITY_TYPE_LABEL] = wk.CAPACITY_TYPE_ON_DEMAND
        elif u < 0.42:
            selector[wk.ARCH_LABEL] = "arm64" if rng.random() < 0.5 else "amd64"
        tolerations = []
        if rng.random() < 0.1:
            tolerations.append(Toleration(key="dedicated", operator="Exists"))
        requests = Resources.from_base_units(
            {
                res.CPU: float(cpu_choices[sizes[t]]),
                res.MEMORY: float(mem_choices[sizes[t]]) * 2**20,
            }
        )
        templates.append((requests, selector, tolerations))

    pods = []
    i = 0
    for t in range(T):
        requests, selector, tolerations = templates[t]
        for _ in range(int(counts[t])):
            pods.append(
                Pod(
                    f"bench-{salt}-{i}",
                    requests=requests,
                    node_selector=selector,
                    tolerations=tolerations,
                    labels={"app": f"app-{salt}-{t}"},
                )
            )
            i += 1
    return pods


def _stage_breakdown(solver, pool, items, pods):
    """One staged decomposition of the solve path (numbers in ms). The
    stages here are run serially with a device sync between solve and
    fetch, so their sum slightly exceeds the pipelined production path."""
    from karpenter_tpu.solver import encode, ffd

    t = {}
    t0 = time.perf_counter()
    classes = encode.group_pods(pods, extra_requirements=pool.requirements())
    t["group"] = time.perf_counter() - t0
    entry = solver._catalog(items)
    catalog, staged, offsets, words = entry.tensors, entry.staged, entry.offsets, entry.words
    t0 = time.perf_counter()
    cs = encode.encode_classes(
        classes, catalog, c_pad=encode.bucket(len(classes), solver.c_pad_min)
    )
    t["encode"] = time.perf_counter() - t0
    t0 = time.perf_counter()
    inp = ffd.make_inputs_staged(staged, cs)
    nnz_max = ffd.nnz_budget(cs.c_pad, solver.g_max)
    buf = ffd.ffd_solve_fused(
        inp, g_max=solver.g_max, nnz_max=nnz_max,
        word_offsets=offsets, words=words, objective=solver.objective,
    )
    # production shape: ONE async copy issued at dispatch, one sync read --
    # a separate block_until_ready would pay the tunnel round trip twice
    buf.copy_to_host_async()
    host_buf = np.asarray(buf)
    t["solve_fetch"] = time.perf_counter() - t0
    t0 = time.perf_counter()
    dense = ffd.expand_fused(
        host_buf, cs.c_pad, solver.g_max, catalog.k_pad, encode.Z_PAD, encode.CT, nnz_max,
    )
    if dense is None:
        # sparse-budget overflow: mirror the production dense refetch
        dense = ffd.solve_dense_tuple(
            inp, g_max=solver.g_max, word_offsets=offsets, words=words,
            objective=solver.objective,
        )
    solver._decode(pool, entry, cs, dense, None)
    t["decode"] = time.perf_counter() - t0
    return {k: round(v * 1e3, 2) for k, v in t.items()}, len(classes)


def _pipelined_ticks(solver, pool, items, rng, zones, k: int, windows: int):
    """Sustained-throughput measurement of the PRODUCTION pipelined path
    (VERDICT r4 item 1b, promoted round 6): K back-to-back COLD ticks
    driven through the exact two halves the provisioner's double-buffered
    tick uses (TPUSolver.solve_begin / solve_finish) -- tick i+1's host
    stages + dispatch run before tick i's barrier, so the result fetch of
    tick i overlaps the next tick's host work. No longer a fenced bench
    reimplementation: the begin/finish session IS the default production
    tick. The per-tick wall reported here is a MEASURED end-to-end number
    with no tunnel term to subtract: each fetch's flat RTT hides under
    the next tick's host stages, so on the bench tunnel the steady state
    is max(host stages, device + RTT) and on a TPU VM (no tunnel) it is
    the compute sum itself. Returns per-window per-tick ms."""
    out = []
    for w in range(windows):
        pods_k = [
            synth_pods(rng, zones, N_PODS, salt=50_000 + w * k + i)
            for i in range(k)
        ]
        pending = None
        t0 = time.perf_counter()
        for pods in pods_k:
            ticket = solver.solve_begin(pool, items, pods)
            if pending is not None:
                solver.solve_finish(pending)
            pending = ticket
        solver.solve_finish(pending)
        out.append((time.perf_counter() - t0) * 1000.0 / k)
    return out


def _rpc_loopback_p50(pool, items, workloads, iters: int) -> float:
    """The tick measured through the PRODUCTION topology (VERDICT r4 item
    1b): solver reached via solver/rpc.py over a local UNIX socket --
    encode, wire framing, device solve, wire return, decode, end to end.
    On the TPU-VM sidecar deployment this loopback path IS the production
    path; here it additionally pays the bench tunnel once per solve."""
    import shutil
    import tempfile

    from karpenter_tpu.solver import rpc
    from karpenter_tpu.solver.service import TPUSolver

    d = tempfile.mkdtemp(prefix="bench_rpc_")
    path = os.path.join(d, "solver.sock")
    srv = None
    client = None
    try:
        srv = rpc.SolverServer(path=path).start()
        client = rpc.SolverClient(path=path)
        s = TPUSolver(g_max=G_MAX, client=client)
        s.solve(pool, items, workloads[0])  # stage catalog + warm the path
        times = []
        for i in range(iters):
            t0 = time.perf_counter()
            s.solve(pool, items, workloads[(i + 1) % len(workloads)])
            times.append((time.perf_counter() - t0) * 1e3)
        return float(np.percentile(times, 50))
    finally:
        if client is not None:
            client.close()
        if srv is not None:
            srv.stop()
        shutil.rmtree(d, ignore_errors=True)


def _mixed_affinity(solver, pool, items, zones, rng, iters: int) -> dict:
    """Mixed-batch datapoint (VERDICT r4 item 2): the 50k tick with ~1%
    affinity pods riding the oracle SUFFIX while the plain majority stays
    on device. Reported next to the pure-batch latency so the carve's
    cost is visible in the artifact."""
    from karpenter_tpu.apis import Pod, labels as wk
    from karpenter_tpu.apis.pod import PodAffinityTerm
    from karpenter_tpu.scheduling import Resources
    from karpenter_tpu.solver.oracle import Scheduler

    def aff_pods(salt, n):
        out = []
        for a in range(n):
            tier = f"bench-aff-{salt}-{a % 16}"
            out.append(Pod(
                f"aff-{salt}-{a}",
                # cpu values disjoint from synth_pods' choices: the carve
                # must never be blocked by an envelope-key collision
                requests=Resources.from_base_units(
                    {"cpu": [150.0, 350.0, 650.0][a % 3],
                     "memory": 256.0 * 2**20}),
                labels={"tier": tier},
                affinity_terms=[PodAffinityTerm(
                    label_selector={"tier": tier},
                    topology_key=wk.HOSTNAME_LABEL)],
            ))
        return out

    n_aff = max(1, N_PODS // 100)
    times = []
    route = {}
    for i in range(iters):
        pods = synth_pods(rng, zones, N_PODS - n_aff, salt=60_000 + i)
        pods += aff_pods(60_000 + i, n_aff)
        sched = Scheduler(
            nodepools=[pool], instance_types={pool.name: items},
            zones=set(zones), objective=solver.objective,
        )
        t0 = time.perf_counter()
        solver.schedule(sched, pods)
        times.append((time.perf_counter() - t0) * 1e3)
        route = dict(solver.last_route)
    total = route.get("device_pods", 0) + route.get("oracle_pods", 0)
    return {
        "mixed_affinity_p50_ms": round(float(np.percentile(times, 50)), 2),
        "mixed_affinity_iters_ms": [round(x, 1) for x in times],
        "mixed_affinity_route": route.get("path", ""),
        "mixed_affinity_device_fraction": round(
            route.get("device_pods", 0) / total, 4) if total else 0.0,
    }


def _traced_rig(n_pods: int) -> dict:
    """Stage-attributed tick measurement (the observability PR): a kwok
    rig driven through the PRODUCTION topology -- pipelined provisioner
    tick, solver behind the rpc sidecar on a local UNIX socket -- with
    tracing at full sampling. Emits per-span-name p50/p99 for the
    canonical stages (snapshot, encode, wire, device, decode, bind, plus
    drain/dispatch/launch and the grafted server fetch), the pipeline
    overlap fraction, and the flight-recorder tree count, so BENCH_*.json
    trajectories become stage-attributable."""
    import shutil
    import tempfile

    from karpenter_tpu import metrics, tracing
    from karpenter_tpu.apis import NodePool, Pod, TPUNodeClass
    from karpenter_tpu.cache.ttl import FakeClock
    from karpenter_tpu.operator import Operator, Options
    from karpenter_tpu.scheduling import Resources
    from karpenter_tpu.solver import rpc
    from karpenter_tpu.solver.service import TPUSolver

    d = tempfile.mkdtemp(prefix="bench_trace_")
    path = os.path.join(d, "solver.sock")
    srv = None
    client = None
    try:
        srv = rpc.SolverServer(path=path).start()
        client = rpc.SolverClient(path=path)
        op = Operator(
            clock=FakeClock(1_000.0),
            solver=TPUSolver(g_max=G_MAX, client=client),
            # slow_ms=0: record EVERY sweep so the artifact can prove the
            # flight recorder held complete trees for this run
            options=Options(
                pipelined_scheduling=True, tracing=True,
                tracing_sample=1.0, tracing_slow_ms=0.0,
            ),
        )
        op.cluster.create(TPUNodeClass("default"))
        op.cluster.create(NodePool("default"))
        tracing.TRACER.reset()
        waves = 6
        per = max(1, n_pods // waves)
        sizes = [("250m", "512Mi"), ("500m", "1Gi"), ("1", "2Gi"), ("2", "4Gi")]
        for w in range(waves):
            for i in range(per):
                cpu, mem = sizes[i % len(sizes)]
                op.cluster.create(
                    Pod(f"tr{w}-{i}", requests=Resources({"cpu": cpu, "memory": mem}))
                )
            op.tick()
            op.clock.step(3.0)
        op.settle(max_ticks=30)
        stats = tracing.TRACER.stats()
        overlap = metrics.PIPELINE_OVERLAP.percentile(50)
        dump = tracing.TRACER.recorder.dump()
        return {
            "trace_stages_ms": {
                k: [v["p50_ms"], v["p99_ms"]] for k, v in sorted(stats.items())
            },
            "trace_stage_counts": {k: v["count"] for k, v in sorted(stats.items())},
            "overlap_fraction_p50": (
                round(overlap, 4) if overlap == overlap else None  # NaN = pipeline never engaged
            ),
            "trace_slow_ticks_recorded": len(dump["slow"]),
            "trace_rig_pods": per * waves,
        }
    finally:
        tracing.TRACER.configure(enabled=False)
        if client is not None:
            client.close()
        if srv is not None:
            srv.stop()
        shutil.rmtree(d, ignore_errors=True)


def _tracing_overhead(solver, pool, items, workloads, iters: int) -> dict:
    """Measured tracing tax on the tier's solve: the same warm workloads
    run with the tracer off and on (full sampling, recorder effectively
    muted), medians compared. The contract is <2%; the artifact carries
    the number so the claim is re-checked every run."""
    from karpenter_tpu import tracing

    offs: list = []
    diffs: list = []
    try:
        # PAIRED off/on measurements with alternating order: each
        # iteration solves the SAME workload twice (once traced, once
        # not) back to back and records the difference, with which side
        # goes first swapping every iteration -- so thermal drift and the
        # pair's warm-cache bias both cancel in the paired difference.
        # The span cost itself is ~15 allocations + clock reads per tick
        # (microseconds), far below a single solve's jitter, which is
        # exactly why an unpaired two-pass comparison cannot resolve it.
        for i in range(iters):
            pods = workloads[i % len(workloads)]
            pair_ms = {}
            order = (False, True) if i % 2 == 0 else (True, False)
            for enabled in order:
                tracing.TRACER.configure(
                    enabled=enabled, sample=1.0, slow_ms=float("inf")
                )
                t0 = time.perf_counter()
                with tracing.TRACER.trace("tick"):
                    solver.solve(pool, items, pods)
                pair_ms[enabled] = (time.perf_counter() - t0) * 1e3
            offs.append(pair_ms[False])
            diffs.append(pair_ms[True] - pair_ms[False])
        # the tracer's own per-tick cost, measured DIRECTLY: a
        # representative tick tree (~17 spans with attributes plus a
        # 2-stage wire graft) built many times. This resolves the
        # microsecond-scale cost the paired diff cannot (a solve's
        # run-to-run jitter is orders of magnitude larger than the span
        # machinery), so the headline overhead_pct is this deterministic
        # cost over the measured tick -- the paired diff rides along as
        # the empirical noise bound.
        tracing.TRACER.configure(enabled=True, sample=1.0, slow_ms=float("inf"))
        reps = 300
        tr = tracing.TRACER
        t0 = time.perf_counter()
        for _ in range(reps):
            with tr.trace("tick"):
                with tr.span("provisioner"):
                    with tr.span("drain") as d:
                        with tr.span("wire"):
                            tr.graft({
                                "trace": {"trace_id": "x", "span_id": "y"},
                                "spans": [
                                    {"name": "device", "start_ms": 0.1, "dur_ms": 30.0},
                                    {"name": "fetch", "start_ms": 30.1, "dur_ms": 1.0},
                                ],
                            })
                        with tr.span("decode"):
                            pass
                        d.set(overlap_fraction=0.9, hidden_ms=40.0, barrier_ms=4.0)
                    with tr.span("snapshot") as s:
                        s.set(pods=50_000, nodepools=1)
                    with tr.span("dispatch", mode="pipelined"):
                        for nm in ("spread", "pack_existing", "encode", "wire_dispatch"):
                            with tr.span(nm):
                                pass
                    with tr.span("launch", groups=30):
                        for _ in range(3):
                            with tr.span("batch", api="create_fleet", items=10):
                                pass
                with tr.span("bind") as b:
                    b.set(bound=50_000)
                with tr.span("disruption"):
                    pass
        tree_cost_ms = (time.perf_counter() - t0) * 1e3 / reps
    finally:
        tracing.TRACER.configure(enabled=False)
    off = float(np.median(offs))
    paired_diff_ms = float(np.median(diffs))
    return {
        "tracing_off_p50_ms": round(off, 2),
        "tracing_span_tree_cost_ms": round(tree_cost_ms, 4),
        "tracing_overhead_pct": round(100.0 * tree_cost_ms / off, 3) if off > 0 else 0.0,
        "tracing_paired_diff_ms": round(paired_diff_ms, 3),
    }


def _observatory_overhead(solver, off_p50_ms: float) -> dict:
    """Measured observatory tax on the tier's tick, the same DIRECT-cost
    method as `_tracing_overhead` (the per-tick work is microseconds --
    far below a solve's run-to-run jitter, so only a deterministic
    repeated-cost measurement can resolve it): one full per-tick
    observatory pass -- idle profiler bracket, span-tree stage summary,
    rate-limited HBM poll (rate-limiting included deliberately: that IS
    the production cost profile), staged-bytes attribution, flight-ring
    append -- built `reps` times against a representative tick tree.
    The headline `observatory_overhead_pct` is that cost over the
    measured untraced tick p50; contract <1%, shipped as the
    `observatory_overhead_ok` boolean."""
    import time as _time

    from karpenter_tpu import tracing
    from karpenter_tpu.obs import flight
    from karpenter_tpu.obs.profiler import PROFILER

    ring = flight.FlightDataRecorder(capacity=256)
    tr = tracing.Tracer(enabled=True, sample=1.0, slow_ms=float("inf"))
    with tr.trace("tick", force=True) as root:
        with tr.span("provisioner"):
            with tr.span("snapshot"):
                pass
            with tr.span("dispatch"):
                for nm in ("spread", "pack_existing", "encode", "wire_dispatch"):
                    with tr.span(nm):
                        pass
            with tr.span("drain"):
                with tr.span("wire"):
                    tr.graft({
                        "trace": {"trace_id": "x", "span_id": "y"},
                        "spans": [
                            {"name": "device", "start_ms": 0.1, "dur_ms": 30.0},
                            {"name": "fetch", "start_ms": 30.1, "dur_ms": 1.0},
                        ],
                    })
                with tr.span("decode"):
                    pass
            with tr.span("launch"):
                pass
        with tr.span("bind"):
            pass
        with tr.span("disruption"):
            pass
    reps = 300
    t0 = _time.perf_counter()
    for _ in range(reps):
        PROFILER.on_tick_start()
        # the SAME record builder the operator's per-tick path calls
        # (flight.build_tick_record): the contract bounds exactly the
        # production work, and a field added there lands in here too
        ring.record(flight.build_tick_record(root, t0, solver=solver))
        PROFILER.on_tick_end()
    tick_cost_ms = (_time.perf_counter() - t0) * 1e3 / reps
    pct = 100.0 * tick_cost_ms / off_p50_ms if off_p50_ms > 0 else 0.0
    return {
        "observatory_tick_cost_ms": round(tick_cost_ms, 4),
        "observatory_overhead_pct": round(pct, 3),
        "observatory_overhead_ok": bool(off_p50_ms > 0 and pct < 1.0),
    }


def _observatory_fields(solver, client=None) -> dict:
    """Device-memory truth persisted next to the retrace counters
    (observatory tentpole): the HBM peak watermark and the staged tensor
    bytes by owner -- the local split plus, when a sidecar client is
    given, the server-side split via the debug op. Best-effort: memory
    accounting must never cost a bench stage its numbers."""
    from karpenter_tpu.obs import hbm

    out: dict = {}
    try:
        hbm.poll(max_age_s=0.0)
        out["device_hbm_peak_bytes"] = int(hbm.peak_bytes_max())
        staged: dict = {}
        staged.update(solver.staged_bytes_by_kind())
        if client is not None:
            server = client.debug_info().get("staged_bytes", {})
            staged.update({f"server_{k}": int(v) for k, v in server.items()})
        out["staged_bytes_by_kind"] = staged
    except Exception:  # noqa: BLE001
        pass
    return out


def _breaker_degraded(pool, items, zones, rng, iters: int) -> dict:
    """Degraded-mode stage (robustness PR): the sidecar is DOWN and the
    circuit breaker OPEN -- a scheduling tick must complete via the
    in-process CPU fallback with NO connect stall. Measures the trip cost
    (the K bounded-connect-failure ticks that open the breaker) and the
    breaker-open tick p50/p99 at a 2k-pod tier (the <100 ms acceptance
    scale; the 50k CPU tick is bounded separately by the degraded SLO in
    docs/operations.md)."""
    import shutil
    import tempfile

    from karpenter_tpu.solver.breaker import CircuitBreaker
    from karpenter_tpu.solver.rpc import SolverClient
    from karpenter_tpu.solver.service import TPUSolver

    n_pods = min(N_PODS, 2_000)
    workloads = [synth_pods(rng, zones, n_pods, salt=90_000 + i) for i in range(3)]
    d = tempfile.mkdtemp(prefix="bench_breaker_")
    try:
        dead = os.path.join(d, "no-sidecar.sock")  # nothing ever listens here
        client = SolverClient(path=dead, timeout=5.0, connect_timeout=0.2)
        # probe backoff pushed past the measurement window: the stage
        # measures the OPEN state, not a recovery race
        breaker = CircuitBreaker(failure_threshold=2, backoff_base=3600.0)
        # g_max sized to the tier, as a 2k-pod deployment's solver would
        # be: the FFD scan cost is driven by group slots x catalog, and
        # carrying the 50k tier's 1024 slots into a 2k measurement would
        # measure a misconfiguration, not the degraded path
        s = TPUSolver(g_max=128, client=client, breaker=breaker)
        trip_ms = []
        guard = 0
        while breaker.state != "open" and guard < 6:
            t0 = time.perf_counter()
            s.solve(pool, items, workloads[guard % len(workloads)])
            trip_ms.append((time.perf_counter() - t0) * 1e3)
            guard += 1
        # one warm solve: the open path dispatches the fused in-process
        # program, whose one-off compile must not land in the percentile
        s.solve(pool, items, workloads[0])
        times = []
        for i in range(iters):
            t0 = time.perf_counter()
            s.solve(pool, items, workloads[i % len(workloads)])
            times.append((time.perf_counter() - t0) * 1e3)
        return {
            "breaker_open_tick_p50_ms": round(float(np.percentile(times, 50)), 2),
            "breaker_open_tick_p99_ms": round(float(np.percentile(times, 99)), 2),
            "breaker_open_tick_pods": n_pods,
            "breaker_trip_ticks_ms": [round(x, 1) for x in trip_ms],
            "breaker_state": breaker.state,
            "breaker_trips": breaker.trips,
        }
    finally:
        shutil.rmtree(d, ignore_errors=True)


def _decision_sig(res):
    """Canonical comparable form of one SchedulingResult (the warm-delta
    stage's per-tick differential: delta vs full must be bit-identical)."""
    return (
        sorted(
            (tuple(sorted(p.metadata.name for p in g.pods)), g.instance_types[0].name)
            for g in res.new_groups
        ),
        sorted(res.existing_assignments.items()),
        sorted(res.unschedulable.items()),
    )


def _warm_delta(pool, items, zones, iters: int) -> dict:
    """Warm steady-state stage (the incremental delta-solve tentpole's
    acceptance measurement). Models the production steady state at the
    N_PODS tier: per tick an ARRIVAL WAVE of churn_fraction x N_PODS pods
    lands (identical template mix each tick -- a steady workload -- plus a
    rotating hot-template surge, the deployment actively scaling), and the
    tick costs what changed: grouping hits the cross-tick signature memo,
    encode hits the per-class row cache, and the wire ships only the dirty
    class rows against the staged class epoch (solver/rpc.py solve_delta).

    Three comparisons land in the JSON line:
    - warm_delta_tick_p50_ms vs warm_full_reference_p50_ms: the steady-
      state tick against the full re-encode tick (the whole N_PODS pending
      set re-grouped/re-encoded/re-shipped -- what every tick cost before
      the incremental engine). The acceptance claim: >= 2x.
    - warm_delta_tick_p50_ms vs warm_full_tick_p50_ms: the same wave
      through the engine with delta + incremental OFF (the engine-only
      win, same batch both sides, decisions asserted identical per tick).
    - payload bytes: delta rows shipped vs the full-tensor ship, same
      shape and vs the full-tier reference. The acceptance claim: >= 5x.

    The tail_ratio assertion (satellite: r05 warm p99 spikes) rides along:
    after freeze_caches() the warm tail must stay within
    BENCH_TAIL_RATIO_MAX (default 3.0) of the p50; the boolean lands in
    the artifact rather than raising (the one-JSON-line contract)."""
    import shutil
    import tempfile

    from karpenter_tpu.solver import rpc
    from karpenter_tpu.solver.oracle import Scheduler
    from karpenter_tpu.solver.service import TPUSolver

    churn_frac = max(0.001, min(0.10, _env_f("BENCH_CHURN_FRACTION", 0.05)))
    wave = max(8, int(N_PODS * churn_frac))
    arrival_templates = min(N_SPEC_TEMPLATES, 40)
    d = tempfile.mkdtemp(prefix="bench_delta_")
    sock = os.path.join(d, "solver.sock")
    srv = client_d = client_f = None

    def sched():
        return Scheduler(
            nodepools=[pool], instance_types={pool.name: items}, zones=set(zones)
        )

    def wave_pods(i: int):
        # identical template mix every tick (fixed rng seed; fresh names
        # via salt) plus a surge on a 3-template universe whose size
        # rotates -- so a handful of class rows are dirty per tick, the
        # steady-state shape the delta wire exists for
        base = synth_pods(np.random.default_rng(1234), zones, wave,
                          salt=70_000 + i, templates=arrival_templates)
        surge_n = 8 + (i % 3) * 7
        surge = synth_pods(np.random.default_rng(99), zones, surge_n,
                           salt=80_000 + i, templates=3)
        return base + surge

    try:
        srv = rpc.SolverServer(path=sock).start()
        client_d = rpc.SolverClient(path=sock, delta=True)
        client_f = rpc.SolverClient(path=sock, delta=False)
        sd = TPUSolver(g_max=G_MAX, client=client_d, incremental=True)
        sf = TPUSolver(g_max=G_MAX, client=client_f, incremental=False)
        # unmeasured warm ticks: compile the wave-tier shapes, establish
        # the delta base epoch, and fill the grouping/row caches
        for w in (wave_pods(-2), wave_pods(-1)):
            sf.schedule(sched(), w)
            sd.schedule(sched(), w)
        # satellite (r05 warm p99 spikes): the staged catalog, row cache,
        # and grouping memos are long-lived after warmup -- freeze them out
        # of every later gen2 walk
        sd.freeze_caches()

        # always-run retrace guard (jax-discipline tentpole): warmup is
        # over, so the measured loop runs inside a witness hot section --
        # ANY XLA compile or unsanctioned device->host transfer during it
        # is a recorded violation, persisted as warm_retrace_count
        # (asserted 0) with the compile-time breakdown riding the PR-5
        # incremental side-file
        from karpenter_tpu.analysis import jax_witness

        if os.environ.get("KARPENTER_TPU_JAX_WITNESS", "1") != "0":
            jax_witness.install()
        wit0 = jax_witness.stats()

        delta_ms, full_ms = [], []
        payload_d, payload_f, rows_shipped, dirty_frac, modes = [], [], [], [], []
        identical = True
        with jax_witness.hot("bench_warm_delta"):
            for i in range(iters):
                pods = wave_pods(i)
                t0 = time.perf_counter()
                res_f = sf.schedule(sched(), pods)
                full_ms.append((time.perf_counter() - t0) * 1e3)
                payload_f.append(client_f.last_delta["payload_bytes"])
                t0 = time.perf_counter()
                res_d = sd.schedule(sched(), pods)
                delta_ms.append((time.perf_counter() - t0) * 1e3)
                ld = dict(client_d.last_delta)
                payload_d.append(ld["payload_bytes"])
                modes.append(ld["mode"])
                if ld["mode"] == "delta":
                    rows_shipped.append(ld["rows"])
                dirty_frac.append(sd.last_group_stats.get("dirty_fraction", 1.0))
                identical = identical and _decision_sig(res_d) == _decision_sig(res_f)
        wit1 = jax_witness.stats()
        warm_retraces = wit1["hot_retraces"] - wit0["hot_retraces"]
        warm_transfers = wit1["hot_transfers"] - wit0["hot_transfers"]
        witness_fields = {
            # jax-witness acceptance: the warm measured loop must neither
            # recompile nor sync unsanctioned -- a nonzero count here IS
            # the multi-second stall class the discipline checker fences.
            # Omitted entirely when the witness is disabled: a gate that
            # measured nothing must not report green.
            "warm_retrace_count": int(warm_retraces),
            "warm_host_transfer_count": int(warm_transfers),
            "warm_retrace_ok": bool(warm_retraces == 0 and warm_transfers == 0),
            "warm_compile_events_total": int(wit1["compiles_total"]),
            "warm_compile_secs_total": wit1["compile_secs_total"],
            "warm_compile_breakdown": wit1["compile_breakdown"],
        } if jax_witness.installed() else {}
        # the full re-encode reference: the whole N_PODS-tier pending set
        # re-grouped, re-encoded, and re-shipped through the same sidecar
        sf.schedule(sched(), synth_pods(
            np.random.default_rng(4321), zones, N_PODS, salt=85_000))  # warm shapes
        ref_ms = []
        for i in range(2):
            full_set = synth_pods(
                np.random.default_rng(4321), zones, N_PODS, salt=85_001 + i)
            t0 = time.perf_counter()
            sf.schedule(sched(), full_set)
            ref_ms.append((time.perf_counter() - t0) * 1e3)
        ref_payload = int(client_f.last_delta["payload_bytes"])

        p50 = float(np.percentile(delta_ms, 50))
        p99 = float(np.percentile(delta_ms, 99))
        full_p50 = float(np.percentile(full_ms, 50))
        ref_p50 = float(np.percentile(ref_ms, 50))
        pay_d = float(np.median(payload_d))
        pay_f = float(np.median(payload_f))
        tail = p99 / p50 if p50 > 0 else 0.0
        return {
            "warm_delta_tick_p50_ms": round(p50, 2),
            "warm_delta_tick_p99_ms": round(p99, 2),
            "warm_delta_iters_ms": [round(x, 1) for x in delta_ms],
            "warm_full_tick_p50_ms": round(full_p50, 2),
            "warm_full_reference_p50_ms": round(ref_p50, 2),
            "warm_delta_speedup_vs_full_tier": round(ref_p50 / p50, 2) if p50 else 0.0,
            "warm_delta_speedup_same_batch": round(full_p50 / p50, 2) if p50 else 0.0,
            "warm_delta_payload_bytes_p50": int(pay_d),
            "warm_full_payload_bytes_p50": int(pay_f),
            "warm_full_reference_payload_bytes": ref_payload,
            "warm_delta_payload_reduction_same_shape": round(pay_f / pay_d, 1) if pay_d else 0.0,
            "warm_delta_payload_reduction_vs_full_tier": round(ref_payload / pay_d, 1) if pay_d else 0.0,
            "warm_delta_rows_shipped_p50": (
                int(np.median(rows_shipped)) if rows_shipped else -1
            ),
            "warm_delta_modes": modes,
            "warm_delta_dirty_fraction_p50": round(float(np.median(dirty_frac)), 4),
            "warm_delta_churn_fraction": churn_frac,
            "warm_delta_wave_pods": wave,
            "warm_delta_decisions_identical": identical,
            "warm_delta_tail_ratio": round(tail, 3),
            "warm_delta_tail_ok": bool(
                tail <= _env_f("BENCH_TAIL_RATIO_MAX", 3.0)
            ),
            **witness_fields,
            **_observatory_fields(sd, client_d),
        }
    finally:
        if client_d is not None:
            client_d.close()
        if client_f is not None:
            client_f.close()
        if srv is not None:
            srv.stop()
        shutil.rmtree(d, ignore_errors=True)


def _wire_stage(pool, items, zones, iters: int) -> dict:
    """Always-run transport stage (the wire-v2 tentpole's acceptance
    measurement). The warm steady-state wave from the delta stage drives
    THREE client configurations against one sidecar on a UNIX socket:

    - shm ring + reply_v2 (the colocated default since wire v2),
    - tcp socket + reply_v2 (the portable fallback),
    - tcp socket + v1 replies (the pre-trim reference).

    Fields: warm_wire_p50/p99_ms (the solver's "wire" span: transport +
    server device + fetch), wire_share_of_tick, the transport-only
    overhead vs the server's device exec (the ROADMAP target: under 2x
    device exec on the capture rig), reply_bytes_per_solve v2 vs v1
    (acceptance: >=3x smaller), and the encode/decode payload-copy
    counters per solve (acceptance: 0 on the warm delta path)."""
    import shutil
    import tempfile

    from karpenter_tpu import metrics, tracing
    from karpenter_tpu.solver import rpc
    from karpenter_tpu.solver.oracle import Scheduler
    from karpenter_tpu.solver.service import TPUSolver

    churn_frac = max(0.001, min(0.10, _env_f("BENCH_CHURN_FRACTION", 0.05)))
    wave = max(8, int(N_PODS * churn_frac))
    arrival_templates = min(N_SPEC_TEMPLATES, 40)
    d = tempfile.mkdtemp(prefix="bench_wire_")
    sock = os.path.join(d, "solver.sock")
    srv = None
    clients = []

    def sched():
        return Scheduler(
            nodepools=[pool], instance_types={pool.name: items}, zones=set(zones)
        )

    def wave_pods(i: int):
        return synth_pods(np.random.default_rng(1234), zones, wave,
                          salt=90_000 + i, templates=arrival_templates)

    def copies() -> float:
        return (metrics.WIRE_PAYLOAD_COPIES.value(side="encode")
                + metrics.WIRE_PAYLOAD_COPIES.value(side="decode"))

    prev = (tracing.TRACER.enabled, tracing.TRACER.sample,
            tracing.TRACER.recorder.slow_ms)
    out: dict = {}
    # retrace guard over the transport stage too: the sidecar's device
    # dispatch runs in this process, so a server-side recompile during
    # the measured warm ticks is caught the same way (the counters land
    # in the tpu_capture wire pass)
    from karpenter_tpu.analysis import jax_witness

    if os.environ.get("KARPENTER_TPU_JAX_WITNESS", "1") != "0":
        jax_witness.install()
    wit0 = jax_witness.stats()
    try:
        srv = rpc.SolverServer(path=sock).start()
        tracing.TRACER.configure(enabled=True, sample=1.0, slow_ms=1e12)
        for label, kw in (
            ("shm", {}),
            ("tcp", {"shm": False}),
            ("tcp_v1", {"shm": False, "reply_v2": False}),
        ):
            client = rpc.SolverClient(path=sock, **kw)
            clients.append(client)
            s = TPUSolver(g_max=G_MAX, client=client, incremental=True)
            # unmeasured warm ticks: compile, stage, establish the delta
            # epoch, fill the grouping/row caches -- then the copy
            # counters must stay FLAT across the measured warm ticks
            for w in (wave_pods(-2), wave_pods(-1)):
                s.schedule(sched(), w)
            tracing.TRACER.reset()
            copies0 = copies()
            tick_ms, reply_bytes = [], []
            with jax_witness.hot(f"bench_wire_{label}"):
                for i in range(iters):
                    pods = wave_pods(i)
                    t0 = time.perf_counter()
                    # spans only record under a root trace (the provisioner
                    # tick provides one in production)
                    with tracing.TRACER.trace("bench_wire_tick"):
                        s.schedule(sched(), pods)
                    tick_ms.append((time.perf_counter() - t0) * 1e3)
                    reply_bytes.append(client.last_reply["bytes"])
            st = tracing.TRACER.stats()
            wire_p50 = float(st.get("wire", {}).get("p50_ms", 0.0))
            wire_p99 = float(st.get("wire", {}).get("p99_ms", 0.0))
            device_p50 = float(st.get("device", {}).get("p50_ms", 0.0))
            tick_p50 = float(np.percentile(tick_ms, 50))
            overhead = max(0.0, wire_p50 - device_p50)
            copies_per_solve = (copies() - copies0) / max(1, iters)
            prefix = {"shm": "warm_wire", "tcp": "warm_wire_tcp",
                      "tcp_v1": "warm_wire_v1"}[label]
            out[f"{prefix}_p50_ms"] = round(wire_p50, 2)
            out[f"{prefix}_p99_ms"] = round(wire_p99, 2)
            out[f"{prefix}_tick_p50_ms"] = round(tick_p50, 2)
            out[f"{prefix}_reply_bytes_per_solve"] = int(np.median(reply_bytes))
            out[f"{prefix}_copies_per_solve"] = round(copies_per_solve, 3)
            if label == "shm":
                out["wire_share_of_tick"] = round(wire_p50 / tick_p50, 3) if tick_p50 else 0.0
                out["wire_device_p50_ms"] = round(device_p50, 2)
                out["wire_transport_overhead_p50_ms"] = round(overhead, 2)
                out["wire_overhead_vs_device_ratio"] = (
                    round(overhead / device_p50, 2) if device_p50 else 0.0
                )
                out["wire_transport_negotiated"] = (
                    "shm" if client._ring is not None else "tcp"
                )
                # device-memory truth for the primary (shm) configuration:
                # HBM watermark + staged bytes by owner, incl. the
                # server-side split via the debug op (observatory PR)
                out.update(_observatory_fields(s, client))
        v2 = out.get("warm_wire_tcp_reply_bytes_per_solve", 0)
        v1 = out.get("warm_wire_v1_reply_bytes_per_solve", 0)
        out["reply_bytes_per_solve"] = out.get("warm_wire_reply_bytes_per_solve", v2)
        out["reply_bytes_reduction_v2"] = round(v1 / v2, 1) if v2 else 0.0
        # acceptance bool (the tail_ratio pattern): the v2 trimming must
        # hold its >=3x reply-byte reduction at whatever tier this bench
        # ran -- a dedup regression at real group counts fails the gate
        out["reply_bytes_reduction_ok"] = bool(
            v2 and v1 / v2 >= _env_f("BENCH_REPLY_REDUCTION_MIN", 3.0)
        )
        out["wire_shm_ring_full_total"] = int(metrics.WIRE_SHM_RING_FULL.value())
        if jax_witness.installed():
            # omitted when the witness is disabled: no green gate from a
            # measurement that never ran
            wit1 = jax_witness.stats()
            wire_retraces = wit1["hot_retraces"] - wit0["hot_retraces"]
            wire_transfers = wit1["hot_transfers"] - wit0["hot_transfers"]
            out["wire_warm_retrace_count"] = int(wire_retraces)
            out["wire_warm_host_transfer_count"] = int(wire_transfers)
            out["wire_warm_retrace_ok"] = bool(
                wire_retraces == 0 and wire_transfers == 0
            )
        return out
    finally:
        tracing.TRACER.configure(enabled=prev[0], sample=prev[1], slow_ms=prev[2])
        tracing.TRACER.reset()
        for c in clients:
            c.close()
        if srv is not None:
            srv.stop()
        shutil.rmtree(d, ignore_errors=True)


def _consolidation_stage(pool, items, iters: int = 6) -> dict:
    """Always-run consolidation stage (device-consolidation tentpole's
    acceptance measurement). A synthetic underutilized fleet at the
    current tier -- every node holding a residual pod after a simulated
    ramp-down -- drives full batched candidate-set sweeps through the
    DisruptEngine: singletons for every candidate plus the price-ranked
    multi-node prefixes and underutilized pairs the controller
    enumerates, with replacement context against this tier's catalog.

    Fields:
    - consolidation_nodes_per_s: candidate nodes judged per second of
      sweep wall time (acceptance: >=100 at the 50k tier);
    - consolidation_sweep_p50/p99_ms, consolidation_sets_per_sweep;
    - consolidation_verdict_differential: device-route vs wire-route
      verdict mismatches over identical inputs, asserted 0 (the
      host == wire == device decision contract, measured not assumed);
    - consolidation_warm_retrace_count: jax-witness retraces/unsanctioned
      transfers across the measured warm sweeps, asserted 0."""
    import shutil
    import tempfile

    from karpenter_tpu.apis import Pod, labels as wk
    from karpenter_tpu.scheduling import Resources
    from karpenter_tpu.scheduling import resources as res
    from karpenter_tpu.solver import rpc
    from karpenter_tpu.solver.disrupt import DisruptEngine, enumerate_pairs
    from karpenter_tpu.solver.oracle import ExistingNode
    from karpenter_tpu.solver.service import TPUSolver

    n_nodes = max(64, min(1024, N_PODS // 48))
    n_cand = min(256, n_nodes)
    rng = np.random.default_rng(7)
    shapes = ((4000, 8 << 30), (8000, 16 << 30), (16000, 32 << 30))
    nodes = []
    for i in range(n_nodes):
        cpu_m, mem = shapes[int(rng.integers(0, len(shapes)))]
        used_cpu = int(rng.integers(200, cpu_m // 4))
        nodes.append(ExistingNode(
            name=f"bench-n{i}",
            labels={wk.HOSTNAME_LABEL: f"bench-n{i}",
                    wk.ZONE_LABEL: "us-central-1a"},
            allocatable=Resources.from_base_units(
                {res.CPU: cpu_m, res.MEMORY: mem, res.PODS: 110}),
            used=Resources.from_base_units(
                {res.CPU: used_cpu, res.MEMORY: mem // 8}),
        ))

    def cand_pods(i: int):
        # the candidate's residual pods: 1-3 small survivors of the ramp-down
        k = 1 + i % 3
        return [
            Pod(f"bench-c{i}-{j}",
                requests=Resources({"cpu": "500m", "memory": "512Mi"}))
            for j in range(k)
        ]

    pods_of = [cand_pods(i) for i in range(n_cand)]
    # the controller's enumeration: singletons, prefixes 2..K, pairs
    sets = [(pods_of[i], [nodes[i].name]) for i in range(n_cand)]
    prefix_k = min(32, n_cand)
    for k in range(2, prefix_k + 1):
        sets.append((
            [p for i in range(k) for p in pods_of[i]],
            [nodes[i].name for i in range(k)],
        ))
    for i, j in enumerate_pairs(n_cand):
        sets.append((pods_of[i] + pods_of[j], [nodes[i].name, nodes[j].name]))

    from karpenter_tpu.analysis import jax_witness

    if os.environ.get("KARPENTER_TPU_JAX_WITNESS", "1") != "0":
        jax_witness.install()
    wit0 = jax_witness.stats()
    d = tempfile.mkdtemp(prefix="bench_consolidate_")
    sock = os.path.join(d, "solver.sock")
    srv = None
    client = None
    out: dict = {}
    try:
        engine = DisruptEngine()
        kw = dict(pools=[pool], catalogs={pool.name: items})
        base = engine.evaluate(nodes, sets, **kw)  # compile + stage, unmeasured
        sweep_ms = []
        with jax_witness.hot("bench_consolidation"):
            for _ in range(iters):
                t0 = time.perf_counter()
                verdicts = engine.evaluate(nodes, sets, **kw)
                sweep_ms.append((time.perf_counter() - t0) * 1e3)
        p50 = float(np.percentile(sweep_ms, 50))
        out["consolidation_sweep_p50_ms"] = round(p50, 2)
        out["consolidation_sweep_p99_ms"] = round(float(np.percentile(sweep_ms, 99)), 2)
        out["consolidation_sets_per_sweep"] = len(sets)
        out["consolidation_candidates_per_sweep"] = n_cand
        out["consolidation_fleet_nodes"] = n_nodes
        out["consolidation_nodes_per_s"] = round(n_cand / (p50 / 1e3), 1) if p50 else 0.0
        out["consolidation_nodes_per_s_ok"] = bool(
            out["consolidation_nodes_per_s"]
            >= _env_f("BENCH_CONSOLIDATION_NODES_PER_S_MIN", 100.0)
        )
        assert [repr(v) for v in verdicts] == [repr(v) for v in base], (
            "warm sweep verdicts drifted across iterations"
        )
        # wire differential: the SAME sweep through a loopback sidecar's
        # solve_disrupt op must produce bit-identical verdicts
        srv = rpc.SolverServer(path=sock).start()
        client = rpc.SolverClient(path=sock)
        solver = TPUSolver(g_max=G_MAX, client=client)
        wire_engine = DisruptEngine(solver=solver)
        wire_verdicts = wire_engine.evaluate(nodes, sets, **kw)
        diff = sum(
            1 for a, b in zip(wire_verdicts, verdicts) if repr(a) != repr(b)
        )
        out["consolidation_wire_path"] = wire_engine.last_dispatch["path"]
        out["consolidation_verdict_differential"] = int(diff)
        out["consolidation_differential_ok"] = bool(
            diff == 0 and wire_engine.last_dispatch["path"] == "wire"
        )
        if jax_witness.installed():
            wit1 = jax_witness.stats()
            retraces = wit1["hot_retraces"] - wit0["hot_retraces"]
            transfers = wit1["hot_transfers"] - wit0["hot_transfers"]
            out["consolidation_warm_retrace_count"] = int(retraces)
            out["consolidation_warm_host_transfer_count"] = int(transfers)
            out["consolidation_warm_retrace_ok"] = bool(
                retraces == 0 and transfers == 0
            )
        return out
    finally:
        if client is not None:
            client.close()
        if srv is not None:
            srv.stop()
        shutil.rmtree(d, ignore_errors=True)


def _recovery_stage(warm_tick_p50_ms=None, iters: int = 4, k_intents: int = 16) -> dict:
    """Crash-recovery stage (crash-consistency tentpole; ALWAYS runs):

    - recovery_sweep_p50/p99_ms: wall time of one restart recovery sweep
      replaying `k_intents` crashed launches (the real crash path: a
      `crash.launch` failpoint kills the fan-out after the cloud mutation,
      leaving open intents + uncommitted instances; a fresh operator over
      the surviving world adopts them all).
    - journal_write_pair_ms_p50: the begin+resolve cost ONE journaled
      launch adds to a tick. Warm steady-state ticks launch nothing, so
      their journal cost is zero by construction; this per-pair cost vs
      warm_delta_tick_p50_ms is the conservative bound the <1% acceptance
      rides on (journal_overhead_ok)."""
    from karpenter_tpu.apis import NodeClaim, NodePool, TPUNodeClass
    from karpenter_tpu.apis.objects import ProvisioningIntent
    from karpenter_tpu.cache.ttl import FakeClock
    from karpenter_tpu.failpoints import FAILPOINTS, OperatorCrashed
    from karpenter_tpu.operator import Operator

    sweep_ms = []
    adopted_total = 0
    for it in range(iters):
        clock = FakeClock(1000.0)
        op = Operator(clock=clock, identity="bench-crash-a")
        op.cluster.create(TPUNodeClass("default"))
        op.cluster.create(NodePool("default"))
        for i in range(k_intents):
            # standalone claims: exactly one launch + intent each (the
            # journaled nodeclaim-lifecycle path), so the sweep replays
            # precisely k_intents adoptions
            op.cluster.create(NodeClaim(f"rec-{it}-{i}"))
        FAILPOINTS.arm("crash.launch", "crash", times=k_intents)
        try:
            op.tick()
        except OperatorCrashed:
            pass
        finally:
            FAILPOINTS.disarm("crash.launch")
        open_n = len(op.cluster.list(ProvisioningIntent))
        clock.step(20.0)
        op2 = Operator(cloud=op.cloud, clock=clock, cluster=op.cluster)
        # sweep() is timed directly (not via tick); adopt the bus epoch
        # first exactly as the elector-less first tick would
        op2.fence.observe(op2.fence.current())
        t0 = time.perf_counter()
        outcomes = op2.recovery.sweep()
        sweep_ms.append((time.perf_counter() - t0) * 1e3)
        adopted_total += outcomes.get("adopted", 0)
        assert open_n and not op2.cluster.list(ProvisioningIntent)

    # journal write overhead: the durable begin+resolve pair per launch
    clock = FakeClock(1000.0)
    op = Operator(clock=clock)
    op.cluster.create(TPUNodeClass("default"))
    op.cluster.create(NodePool("default"))
    pair_ms = []
    for i in range(200):
        claim = NodeClaim(f"jw-{i}")
        op.cluster.create(claim)
        t0 = time.perf_counter()
        intent = op.journal.begin_launch(claim)
        op.journal.resolve(intent, "committed")
        pair_ms.append((time.perf_counter() - t0) * 1e3)

    out = {
        "recovery_sweep_p50_ms": round(float(np.percentile(sweep_ms, 50)), 3),
        "recovery_sweep_p99_ms": round(float(np.percentile(sweep_ms, 99)), 3),
        "recovery_sweep_intents": k_intents,
        "recovery_sweep_adopted_total": adopted_total,
        "journal_write_pair_ms_p50": round(float(np.percentile(pair_ms, 50)), 4),
    }
    if warm_tick_p50_ms:
        pct = 100.0 * out["journal_write_pair_ms_p50"] / warm_tick_p50_ms
        out["journal_write_overhead_pct_of_warm_tick"] = round(pct, 3)
        out["journal_overhead_ok"] = bool(pct < 1.0)
    return out


def _overload_stage(iters_per_load: int = 6, tier_pods: int = 10_000) -> dict:
    """Overload stage (overload-control tentpole; ALWAYS runs): an
    offered-load sweep at 1x / 3x / 10x of a base arrival rate sized to
    the 10k tier, through the production topology (sidecar + pipelined
    tick) with the tick deadline budget armed. Headlines:

    - overload_tick_p99_ms: storm-tick (10x) wall p99 -- the acceptance
      bound is <= 2x the deadline (overload_p99_within_2x_deadline);
    - shed_fraction: pods deferred by bounded admission over pods offered
      during the 10x phase (the early-shed actually engaging);
    - overload_recover_s: wall time from end-of-storm until the pending
      set drains (every shed pod placed -- the zero-pods-lost half).

    The deadline is self-calibrated at 2x the measured 1x-load tick p99
    (p99, not p50: one XLA recompile or gen2 GC inside a calibration
    tick must not fail the acceptance bool on noise), so the stage
    measures OVERLOAD behavior, not this host's absolute speed."""
    import shutil
    import tempfile

    from karpenter_tpu import metrics
    from karpenter_tpu.apis import NodePool, Pod, TPUNodeClass
    from karpenter_tpu.cache.ttl import FakeClock
    from karpenter_tpu.operator import Operator, Options
    from karpenter_tpu.scheduling import Resources
    from karpenter_tpu.solver import rpc
    from karpenter_tpu.solver.service import TPUSolver

    sizes = [("250m", "512Mi"), ("500m", "1Gi"), ("1", "2Gi"), ("2", "4Gi")]
    base = max(20, tier_pods // 100)  # per-tick arrivals at 1x

    def build(d, deadline: float):
        path = os.path.join(d, f"solver-ov-{deadline}.sock")
        srv = rpc.SolverServer(path=path).start()
        client = rpc.SolverClient(path=path)
        op = Operator(
            clock=FakeClock(1_000.0),
            solver=TPUSolver(g_max=G_MAX, client=client),
            options=Options(
                pipelined_scheduling=True, tracing=False,
                tick_deadline=deadline, admission_max_pods=2 * base,
            ),
        )
        op.cluster.create(TPUNodeClass("default"))
        op.cluster.create(NodePool("default"))
        return srv, client, op

    def storm(op, mult: int, ticks: int, tag: str):
        ms = []
        for k in range(ticks):
            for i in range(base * mult):
                cpu, mem = sizes[i % len(sizes)]
                op.cluster.create(Pod(
                    f"ov{tag}-{k}-{i}",
                    requests=Resources({"cpu": cpu, "memory": mem}),
                ))
            t0 = time.perf_counter()
            op.tick()
            ms.append((time.perf_counter() - t0) * 1e3)
            op.clock.step(3.0)
        return ms

    def drain(op, max_ticks: int = 400) -> float:
        t0 = time.perf_counter()
        for _ in range(max_ticks):
            if not op.cluster.pending_pods() and op.provisioner._inflight is None:
                break
            op.tick()
            op.clock.step(3.0)
        return time.perf_counter() - t0

    d = tempfile.mkdtemp(prefix="bench_overload_")
    rigs = []
    try:
        # calibration rig: unclamped, 1x load -> the deadline baseline
        srv, client, op = build(d, deadline=3600.0)
        rigs.append((srv, client))
        warm = storm(op, 1, 3, "w")
        del warm
        cal = storm(op, 1, iters_per_load, "c")
        drain(op)
        # deadline = 2x the UNLOADED tick p99: the acceptance bound then
        # reads "a 10x storm costs at most ~4x the unloaded tail" --
        # calibrating on p50 proved too tight on tail-heavy CPU rigs
        # (an XLA recompile or gen2 GC inside one calibration tick would
        # fail the bool on noise, not on overload behavior)
        deadline_s = max(0.25, 2.0 * float(np.percentile(cal, 99)) / 1e3)
        # measurement rig: the self-calibrated deadline armed
        srv2, client2, op2 = build(d, deadline=deadline_s)
        rigs.append((srv2, client2))
        storm(op2, 1, 2, "w2")  # warm the second rig's caches
        drain(op2)
        by_load = {}
        offered_10x = 0
        backlog_10x = 0.0
        recover_s = 0.0
        for mult in (1, 3, 10):
            ms = storm(op2, mult, iters_per_load, f"m{mult}")
            by_load[f"{mult}x"] = round(float(np.percentile(ms, 99)), 2)
            if mult == 10:
                offered_10x = base * mult * iters_per_load
                # shed_fraction = the 10x phase's offered pods still
                # DEFERRED when the storm ended (the last tick's deferral
                # gauge) -- a backlog fraction in [0, ~1], not a per-tick
                # re-shed event count (a deferred pod re-sheds every tick
                # it waits, so the raw counter over-counts by queue depth)
                backlog_10x = metrics.OVERLOAD_DEFERRED.value()
                recover_s = drain(op2)
            else:
                drain(op2)
        pending_left = len(op2.cluster.pending_pods())
        p99_10x = by_load["10x"]
        return {
            "overload_tick_p99_ms": p99_10x,
            "overload_tick_p99_by_load_ms": by_load,
            "overload_deadline_ms": round(deadline_s * 1e3, 1),
            "overload_p99_within_2x_deadline": bool(p99_10x <= 2_000.0 * deadline_s),
            "shed_fraction": round(backlog_10x / offered_10x, 4) if offered_10x else 0.0,
            "overload_recover_s": round(recover_s, 2),
            "overload_pods_lost": pending_left,  # MUST read 0
            "overload_base_arrivals_per_tick": base,
            "overload_brownout_level_final": int(
                metrics.OVERLOAD_BROWNOUT_LEVEL.value()),
        }
    finally:
        from karpenter_tpu import overload as _ov

        _ov.install_brownout(None)
        for srv_i, client_i in rigs:
            client_i.close()
            srv_i.stop()
        shutil.rmtree(d, ignore_errors=True)


def synth_fleet_pods(rng: np.random.Generator, zones, n_pods: int, templates: int):
    """The fleet tier's pending set: like synth_pods but with per-template
    jittered CPU requests so `templates` distinct deployment specs produce
    ~`templates` distinct pod CLASSES (the 2k-type tier needs a class
    universe to match -- the 10x10 request grid of the 50k tier tops out
    near a few hundred)."""
    from karpenter_tpu.apis import Pod, labels as wk
    from karpenter_tpu.scheduling import Resources, Toleration
    from karpenter_tpu.scheduling import resources as res

    cpu_choices = np.array([100, 250, 500, 1000, 2000, 4000, 8000])
    mem_choices = np.array([128, 256, 512, 1024, 2048, 4096, 8192, 16384])
    T = templates
    weights = rng.dirichlet(np.ones(T) * 0.5)
    counts = np.maximum(1, (weights * n_pods).astype(np.int64))
    counts[0] += n_pods - counts.sum()
    pods = []
    i = 0
    for t in range(T):
        cpu = float(cpu_choices[int(rng.integers(0, len(cpu_choices)))]) + float(t % 199)
        mem = float(mem_choices[int(rng.integers(0, len(mem_choices)))])
        selector = {}
        u = rng.random()
        if u < 0.15:
            selector[wk.ZONE_LABEL] = str(zones[int(rng.integers(0, len(zones)))])
        elif u < 0.28:
            selector[wk.CAPACITY_TYPE_LABEL] = wk.CAPACITY_TYPE_ON_DEMAND
        tolerations = (
            [Toleration(key="dedicated", operator="Exists")] if rng.random() < 0.08 else []
        )
        requests = Resources.from_base_units(
            {res.CPU: cpu, res.MEMORY: mem * 2**20}
        )
        for _ in range(int(counts[t])):
            pods.append(Pod(
                f"fleet-{i}", requests=requests, node_selector=selector,
                tolerations=tolerations, labels={"app": f"fleet-app-{t}"},
            ))
            i += 1
    return pods


def _fleet_catalog(items, n_types: int, k_pad=None):
    """A `n_types`-type catalog synthesized from the real 627-type encode:
    rows tile with deterministic price jitter (distinct per clone, so the
    price objective distinguishes them), vocab/zone/word geometry shared
    with the base. Names only matter to decode, which this tensor-tier
    stage never reaches."""
    from karpenter_tpu.solver import encode

    base = encode.encode_catalog(items)
    if k_pad is None:
        # power-of-two bucket >= 128: always divisible by the mesh axes
        k_pad = encode.bucket(n_types, 128)
    idx = (np.arange(n_types) % base.k_real).astype(np.int64)
    rng = np.random.default_rng(7701)
    jitter = (0.85 + 0.3 * rng.random(n_types)).astype(np.float32)

    def tile(a, fill=0):
        out = np.full((k_pad,) + a.shape[1:], fill, dtype=a.dtype)
        out[:n_types] = a[idx]
        return out

    price = np.full((k_pad,) + base.price.shape[1:], np.inf, dtype=np.float32)
    price[:n_types] = base.price[idx] * jitter[:, None, None]
    return encode.CatalogTensors(
        names=[f"{base.names[i]}-v{k // base.k_real}" for k, i in enumerate(idx)],
        k_real=n_types, k_pad=k_pad,
        cap=tile(base.cap), tcode=tile(base.tcode), tnum=tile(base.tnum),
        tnum_present=tile(base.tnum_present), tzone=tile(base.tzone),
        tcap=tile(base.tcap), price=price,
        vocabs=base.vocabs, zones=list(base.zones), words=list(base.words),
    )


def _available_gib() -> float:
    """MemAvailable from /proc/meminfo (GiB); inf when unreadable (no
    basis to skip on)."""
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return float(line.split()[1]) / (1024 * 1024)
    except (OSError, ValueError, IndexError):
        pass
    return float("inf")


def _rig_caveats(platform: str, g_max: int, full_g: int) -> list:
    """Honest-measurement caveats for degraded rigs, embedded in the
    emitted JSON so a reader of the artifact sees the caps without
    cross-referencing the env that produced the run."""
    caveats = []
    if platform == "cpu":
        if g_max < full_g:
            caveats.append(
                f"g_max capped to {g_max} on the CPU rig (the accelerator "
                f"runs the full {full_g}-slot budget); unplaced overflow "
                "is reported, not hidden"
            )
        caveats.append(
            "CPU 'devices' are XLA host threads timeslicing one machine: "
            "mesh numbers exercise the sharded program's semantics, not "
            "multi-host DCN bandwidth"
        )
    return caveats


def _quality_stage(pool, items, zones, rng, warm_tick_p50_ms=None,
                   iters: int = 30, platform: str = "cpu") -> dict:
    """Solution-quality stage (quality-observatory tentpole): ALWAYS
    runs. Proves three things about the in-jit fractional price bound
    (solver/bound.py + obs/quality.py):

    - soundness: optimality_gap = realized fleet price / bound >= 1.0 at
      the 10k and 50k tiers (a gap below 1 means the "lower bound"
      exceeded a real feasible solution -- the bound is wrong, not the
      solver good);
    - cost: the bound dispatch + fetch measured ALONE over N iterations
      lands under 1% of the warm tick p50 (the observatory must not tax
      the tick it observes);
    - discipline: the measured loop runs inside a jax-witness hot
      section, so any retrace or unsanctioned host transfer is a
      recorded violation (fetch_bound is the one SANCTIONED seam).
    """
    from karpenter_tpu.analysis import jax_witness
    from karpenter_tpu.solver import bound as bound_mod
    from karpenter_tpu.solver.service import TPUSolver

    out: dict = {}
    captured: dict = {}
    solver = None
    for tier in sorted({min(N_PODS, 10_000), min(N_PODS, 50_000)}):
        solver = TPUSolver(g_max=G_MAX)
        pods = synth_pods(rng, zones, tier, salt=91_000 + tier)
        solver.solve(pool, items, pods)  # compile + stage
        # capture the bound's own inputs off the warm solve so the cost
        # loop below measures exactly the dispatch production pays
        orig = solver._dispatch_bound

        def _capture(inp, placed, offsets, words, _orig=orig):
            captured.update(inp=inp, placed=placed,
                            offsets=offsets, words=words)
            return _orig(inp, placed, offsets=offsets, words=words)

        solver._dispatch_bound = _capture
        try:
            solver.solve(pool, items, pods)
        finally:
            solver._dispatch_bound = orig
        q = dict(solver.last_quality or {})
        gap = q.get("optimality_gap")
        assert gap is not None and gap >= 1.0, (
            f"fractional bound unsound at the {tier}-pod tier: gap={gap}")
        tag = f"{tier // 1000}k"
        out[f"quality_gap_{tag}"] = round(float(gap), 4)
        out[f"quality_bound_per_h_{tag}"] = round(float(q["bound_per_h"]), 4)
        out[f"quality_realized_per_h_{tag}"] = round(
            float(q["realized_per_h"]), 4)
        out[f"quality_binding_resource_{tag}"] = q.get("binding_resource")
        out[f"quality_stranded_cpu_{tag}"] = round(
            float(q.get("stranded_cpu_fraction", 0.0)), 4)
        out[f"quality_stranded_memory_{tag}"] = round(
            float(q.get("stranded_memory_fraction", 0.0)), 4)
        out[f"quality_fragmentation_{tag}"] = round(
            float(q.get("fragmentation_index", 0.0)), 4)

    # bound cost, measured ALONE on the top tier's captured inputs:
    # dispatch + the blocking fetch, inside a witness hot section
    wit0 = jax_witness.stats() if jax_witness.installed() else None
    cost_ms = []
    with jax_witness.hot("bench_quality_bound"):
        for _ in range(iters):
            t0 = time.perf_counter()
            totals = solver._dispatch_bound(
                captured["inp"], captured["placed"],
                offsets=captured["offsets"], words=captured["words"])
            bound_mod.fetch_bound(totals)
            cost_ms.append((time.perf_counter() - t0) * 1e3)
    cost_p50 = float(np.percentile(cost_ms, 50))
    out["quality_bound_cost_ms"] = round(cost_p50, 4)
    out["quality_bound_cost_p99_ms"] = round(float(np.percentile(cost_ms, 99)), 4)
    if wit0 is not None:
        wit1 = jax_witness.stats()
        out["quality_retrace_count"] = int(
            wit1["hot_retraces"] - wit0["hot_retraces"])
        out["quality_host_transfer_count"] = int(
            wit1["hot_transfers"] - wit0["hot_transfers"])
        out["quality_retrace_ok"] = bool(
            out["quality_retrace_count"] == 0
            and out["quality_host_transfer_count"] == 0)
    if warm_tick_p50_ms and warm_tick_p50_ms > 0:
        share = cost_p50 / float(warm_tick_p50_ms)
        out["quality_bound_share_of_warm_tick"] = round(share, 5)
        assert share < 0.01, (
            f"bound cost {cost_p50:.3f}ms is {share:.1%} of the "
            f"{warm_tick_p50_ms:.1f}ms warm tick (budget: <1%)")
    return out


def _convex_stage(pool, items, zones, rng, iters: int = 10,
                  platform: str = "cpu") -> dict:
    """Convex global-solve tier stage (solver/convex tentpole): ALWAYS
    runs. The tier's cost-and-quality card at the 10k and 50k tiers,
    measured through the production TPUSolver path:

    - convex_tick_p50/p99 vs ffd_tick_p50: the same warm workload
      solved by a pure-FFD solver and a tier="convex" solver (relax
      dispatch + fetch + rounding + the never-worse differential), so
      the overhead ratio is exactly what opting in costs a tick;
    - gap_after_convex vs gap_after_ffd: the optimality gap the quality
      observatory reports under each tier -- the convex lower bound
      tightens the gap denominator even when FFD's placement wins;
    - convex_iterations: subgradient iterations to convergence out of
      the fixed DEFAULT_ITERS budget (solver/convex/relax.py);
    - never-worse acceptance: the realized fleet price under the convex
      tier must not exceed the pure-FFD tier's on the same workload
      (solver/convex/tier.py's choose() differential, asserted here
      end to end).
    """
    from karpenter_tpu.solver.service import TPUSolver

    out: dict = {}
    for tier_n in sorted({min(N_PODS, 10_000), min(N_PODS, 50_000)}):
        tag = f"{tier_n // 1000}k"
        pods = synth_pods(rng, zones, tier_n, salt=97_000 + tier_n)
        ffd = TPUSolver(g_max=G_MAX)
        cx = TPUSolver(g_max=G_MAX, tier="convex")
        ffd.solve(pool, items, pods)  # compile + stage
        cx.solve(pool, items, pods)
        ffd_ms, cx_ms = [], []
        for _ in range(iters):
            t0 = time.perf_counter()
            ffd.solve(pool, items, pods)
            ffd_ms.append((time.perf_counter() - t0) * 1e3)
        for _ in range(iters):
            t0 = time.perf_counter()
            cx.solve(pool, items, pods)
            cx_ms.append((time.perf_counter() - t0) * 1e3)
        ffd_p50 = float(np.percentile(ffd_ms, 50))
        cx_p50 = float(np.percentile(cx_ms, 50))
        out[f"ffd_tick_p50_{tag}_ms"] = round(ffd_p50, 2)
        out[f"convex_tick_p50_{tag}_ms"] = round(cx_p50, 2)
        out[f"convex_tick_p99_{tag}_ms"] = round(
            float(np.percentile(cx_ms, 99)), 2)
        if ffd_p50 > 0:
            out[f"convex_tick_overhead_{tag}"] = round(cx_p50 / ffd_p50, 3)
        q_ffd = dict(ffd.last_quality or {})
        q_cx = dict(cx.last_quality or {})
        out[f"gap_after_ffd_{tag}"] = round(
            float(q_ffd.get("optimality_gap", 0.0)), 4)
        out[f"gap_after_convex_{tag}"] = round(
            float(q_cx.get("optimality_gap", 0.0)), 4)
        lc = dict(cx.last_convex or {})
        out[f"convex_winner_{tag}"] = lc.get("winner")
        out[f"convex_iterations_{tag}"] = lc.get("iterations")
        # never-worse acceptance on choose()'s OWN metric (cheapest
        # surviving offering per group under the candidate's masks):
        # the chosen candidate must not price above the FFD candidate.
        # realized_per_h is NOT comparable across tiers -- it prices
        # instance_types[0] unconstrained by the group's zone/captype
        # masks, an estimator that can flip by a fraction of a percent
        p_ffd_m = lc.get("price_ffd")
        p_cx_m = lc.get("price_convex")
        if p_ffd_m is not None:
            chosen = (p_cx_m if lc.get("winner") == "convex" else p_ffd_m)
            out[f"convex_price_ffd_{tag}"] = round(float(p_ffd_m), 4)
            out[f"convex_price_chosen_{tag}"] = round(float(chosen), 4)
            assert float(chosen) <= float(p_ffd_m) * (1.0 + 1e-9), (
                f"convex tier chose a candidate pricing ${chosen}/h over "
                f"FFD's ${p_ffd_m}/h at the {tag} tier: the never-worse "
                f"differential is broken")
    return out


def _mesh_degrade_stage(pool, items, zones, rng, iters: int = 6,
                        platform: str = "cpu") -> dict:
    """Mesh degrade stage (mesh fault-tolerance tentpole): ALWAYS runs.
    The degrade ladder's cost card, measured at the 2k-pod tier through
    the production TPUSolver-over-MeshSolveEngine path:

    - mesh_reshard_p50/p99_ms: the topology swap ALONE (mesh rebuild +
      sharding-table re-derivation at the _sync_topology seam), programs
      already warm on both layouts -- the latency a tick pays the first
      time it dispatches after a membership change, minus the solve;
    - mesh_shrunk_warm_tick_delta_ms: warm tick p50 on the shrunk
      power-of-two layout vs the full mesh (the steady-state tax of
      running degraded);
    - mesh_quarantine_first_tick_ms: the tick immediately after the
      straggler watchdog's quarantine rung fires (reshard + catalog
      restage + dispatch), against the full-mesh warm p50.
    """
    import jax

    from karpenter_tpu.fleet.shard import MeshSolveEngine
    from karpenter_tpu.parallel.mesh import make_mesh
    from karpenter_tpu.solver.service import TPUSolver

    n_dev = min(8, len(jax.devices()))
    if n_dev < 2:
        return {"mesh_degrade_skipped":
                f"{n_dev} device(s): no mesh to degrade"}
    engine = MeshSolveEngine(make_mesh(n_dev))
    n_pods = min(N_PODS, 2_000)
    # g_max sized to the tier (see _breaker_degraded): the scan cost is
    # slots x catalog, and the full 1024-slot budget at 2k pods would
    # measure a misconfiguration, not the degrade ladder
    g_max = 128
    s = TPUSolver(g_max=g_max, mesh=engine)
    workloads = [synth_pods(rng, zones, n_pods, salt=91_000 + i)
                 for i in range(3)]

    def tick_ms(i: int) -> float:
        t0 = time.perf_counter()
        s.solve(pool, items, workloads[i % len(workloads)])
        return (time.perf_counter() - t0) * 1e3

    # warm each layout once first: the one-off compile must not land in
    # any percentile (losing 1 of n_dev shrinks to the pow2 prefix)
    tick_ms(0)
    full = [tick_ms(i) for i in range(iters)]
    engine.mark_device_lost(n_dev - 1, reason="bench")
    tick_ms(0)
    shrunk = [tick_ms(i) for i in range(iters)]
    engine.mark_device_returned(n_dev - 1)
    tick_ms(0)

    # the swap alone: flip membership, time _sync_topology (the seam
    # every dispatch crosses), both directions in the sample set
    reshard = []
    for i in range(max(iters, 4)):
        if i % 2 == 0:
            engine.mark_device_lost(n_dev - 1, reason="bench")
        else:
            engine.mark_device_returned(n_dev - 1)
        t0 = time.perf_counter()
        engine._sync_topology()
        reshard.append((time.perf_counter() - t0) * 1e3)
    for idx in sorted(engine.topology.quarantined()):
        engine.mark_device_returned(idx)
    engine._sync_topology()

    # quarantine rung: the first tick after quarantine_worst_device
    # (reshard + catalog restage + dispatch, warm programs)
    engine.quarantine_worst_device(reason="bench")
    quarantine_tick = tick_ms(1)
    for idx in sorted(engine.topology.quarantined()):
        engine.mark_device_returned(idx)

    full50 = float(np.percentile(full, 50))
    shrunk50 = float(np.percentile(shrunk, 50))
    return {
        "mesh_degrade_devices": n_dev,
        "mesh_degrade_pods": n_pods,
        "mesh_reshard_p50_ms": round(float(np.percentile(reshard, 50)), 3),
        "mesh_reshard_p99_ms": round(float(np.percentile(reshard, 99)), 3),
        "mesh_full_warm_tick_p50_ms": round(full50, 2),
        "mesh_shrunk_warm_tick_p50_ms": round(shrunk50, 2),
        "mesh_shrunk_warm_tick_delta_ms": round(shrunk50 - full50, 2),
        "mesh_quarantine_first_tick_ms": round(quarantine_tick, 2),
        "mesh_quarantine_tick_over_warm": round(
            quarantine_tick / full50, 2) if full50 > 0 else 0.0,
        "mesh_degrade_rig_caveats": _rig_caveats(platform, g_max, g_max) + [
            "reshard_ms measures the program/sharding swap on an "
            "already-detected loss; real chip-failure detection latency "
            "(the XLA runtime surfacing the error) is not on this rig's "
            "path"
        ],
    }


def _fleet_stage(items, zones, progress=lambda ev: None,
                 stage_fields=lambda fields: None, platform: str = "cpu") -> dict:
    """The 500k-pod / 2k-type FLEET tier (`make bench-fleet`): the
    mesh-sharded production solve at 10x the standing tier, plus the
    multi-tenant coalescing gain. Headline fields:

    - fleet_warm_tick_p50/p99_ms: sharded fused solve + fetch, warm, at
      500k pods x 2k types x 2k classes (the encode runs once -- this is
      the device-tier number; the host encode cost is its own field);
    - fleet_allgather_ms / fleet_allgather_share_of_device_exec: the
      in-jit all-gather's cost, estimated as replicated-out minus
      sharded-out wall time on the same entry (labeled an estimate);
    - fleet_coalescing_gain: N tenants' solves through one coalescing
      sidecar, concurrent wall time vs sequential-isolated wall time
      (>1 = the shared dispatch window wins; ~1 expected on a 1-core
      CPU rig -- the chip is where the overlap pays).

    Memory-aware skip: the tier allocates ~500k Pod objects plus the
    [C, K] mask set; below FLEET_MIN_AVAILABLE_GB available the stage
    returns a skip marker instead of OOMing the rig (the skip is itself
    a headline field, persisted via the side-file like everything else).
    Scale knobs are env-overridable for smoke tests; the driver's
    artifact runs the defaults."""
    import functools as _functools

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from karpenter_tpu.fleet.shard import MeshSolveEngine
    from karpenter_tpu.parallel.mesh import TYPES_AXIS, make_mesh
    from karpenter_tpu.solver import encode, ffd

    n_pods = _env_i("FLEET_PODS", 500_000)
    n_types = _env_i("FLEET_TYPES", 2_000)
    templates = _env_i("FLEET_TEMPLATES", 2_000)
    # group budget: the accelerator runs the full production budget; the
    # degraded CPU rig bounds the scan (the scan length is the dominant
    # cost there; a capped budget keeps the stage inside the wall budget
    # and unplaced overflow is reported, not hidden)
    g_default = 1_024 if platform != "cpu" else 128
    g_max = _env_i("FLEET_G_MAX", g_default)
    iters = _env_i("FLEET_ITERS", 3 if platform != "cpu" else 2)
    min_gib = _env_f("FLEET_MIN_AVAILABLE_GB", 6.0)
    out: dict = {
        "fleet_pods": n_pods, "fleet_types": n_types, "fleet_g_max": g_max,
        "rig_caveats": _rig_caveats(platform, g_max, 1_024),
    }
    if platform == "cpu" and g_max < 1_024:
        out["fleet_g_max_capped_for_cpu"] = True
    avail = _available_gib()
    if avail < min_gib:
        out["fleet_skipped"] = (
            f"memory-aware skip: {avail:.1f} GiB available < "
            f"{min_gib:.1f} GiB floor for the {n_pods // 1000}k-pod tier"
        )
        return out

    n_dev = min(8, len(jax.devices()))
    mesh = make_mesh(n_dev)
    engine = MeshSolveEngine(mesh)
    out["fleet_mesh_devices"] = n_dev

    # host encode: 500k pods -> ~2k classes (measured once; the warm tick
    # pays only churn via the incremental grouper in production)
    rng = np.random.default_rng(4242)
    t0 = time.perf_counter()
    pods = synth_fleet_pods(rng, zones, n_pods, templates)
    t_pods = time.perf_counter() - t0
    progress({"ev": "phase", "name": "fleet_synth", "secs": round(t_pods, 1)})
    t0 = time.perf_counter()
    classes = encode.group_pods(pods)
    cat = _fleet_catalog(items, n_types)
    cs = encode.encode_classes(
        classes, cat, c_pad=encode.bucket(len(classes), 16),
    )
    out["fleet_classes"] = len(classes)
    out["fleet_encode_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
    out["fleet_synth_ms"] = round(t_pods * 1e3, 1)
    stage_fields(dict(out))
    progress({"ev": "phase", "name": "fleet_encode"})
    del pods  # the tensor tier owns the rest; free ~GBs before the solve

    staged, offsets, words = engine.stage_catalog(cat)
    inp = ffd.make_inputs_staged(staged, cs)
    nnz_max = ffd.nnz_budget(cs.c_pad, g_max)
    kw = dict(g_max=g_max, nnz_max=nnz_max, word_offsets=offsets, words=words)

    # compile + warm (one shot), then the measured warm loop
    t0 = time.perf_counter()
    buf = engine.solve_fused(inp, **kw)
    host = np.asarray(buf)
    out["fleet_compile_s"] = round(time.perf_counter() - t0, 1)
    out["fleet_unplaced_pods"] = int(
        host[2 : 2 + cs.c_pad].view(np.int32).sum()
    )
    progress({"ev": "phase", "name": "fleet_compile", "secs": out["fleet_compile_s"]})
    ticks = []
    for wi in range(max(iters, 2)):
        t0 = time.perf_counter()
        buf = engine.solve_fused(inp, **kw)
        np.asarray(buf)
        ticks.append((time.perf_counter() - t0) * 1e3)
        progress({"ev": "phase", "name": f"fleet_warm_{wi}"})
    out["fleet_warm_tick_p50_ms"] = round(float(np.percentile(ticks, 50)), 1)
    out["fleet_warm_tick_p99_ms"] = round(float(np.percentile(ticks, 99)), 1)
    stage_fields(dict(out))

    # all-gather share estimate: the DENSE entry with its gmask output
    # LEFT K-SHARDED (no in-jit gather; every other leaf replicated) vs
    # the production replicated-out entry; the delta is the gather +
    # re-layout cost. The fused entry's 1-D concat has no shardable
    # axis, so the dense twin stands in for the estimate.
    body = _functools.partial(
        ffd.ffd_solve_impl, g_max=g_max, word_offsets=offsets, words=words,
        objective="price",
    )
    rep_sh = NamedSharding(mesh, P())
    k_sh = NamedSharding(mesh, P(None, TYPES_AXIS))
    out_sharded = ffd.SolveOutputs(
        take=rep_sh, unplaced=rep_sh, n_open=rep_sh, accum=rep_sh,
        gmask=k_sh, gzone=rep_sh, gcap=rep_sh, compat=k_sh,
    )
    sharded_out = jax.jit(
        body, in_shardings=(engine._in_shardings,), out_shardings=out_sharded,
    )
    dense_rep = jax.jit(
        body, in_shardings=(engine._in_shardings,), out_shardings=rep_sh,
    )
    jax.block_until_ready(sharded_out(inp))  # compile
    progress({"ev": "phase", "name": "fleet_allgather_compile"})
    jax.block_until_ready(dense_rep(inp))
    progress({"ev": "phase", "name": "fleet_dense_compile"})
    t_sh = []
    for wi in range(max(iters, 2)):
        t0 = time.perf_counter()
        jax.block_until_ready(sharded_out(inp))
        t_sh.append((time.perf_counter() - t0) * 1e3)
        progress({"ev": "phase", "name": f"fleet_sharded_out_{wi}"})
    t_rep = []
    for wi in range(max(iters, 2)):
        t0 = time.perf_counter()
        jax.block_until_ready(dense_rep(inp))
        t_rep.append((time.perf_counter() - t0) * 1e3)
        progress({"ev": "phase", "name": f"fleet_replicated_out_{wi}"})
    rep50, sh50 = float(np.percentile(t_rep, 50)), float(np.percentile(t_sh, 50))
    out["fleet_allgather_ms"] = round(max(rep50 - sh50, 0.0), 2)
    out["fleet_allgather_share_of_device_exec"] = round(
        max(rep50 - sh50, 0.0) / rep50, 4
    ) if rep50 > 0 else 0.0

    stage_fields(dict(out))

    # single-device same-shape reference: the sharded-vs-single ratio
    t0 = time.perf_counter()
    single = ffd.ffd_solve_fused(inp, **kw)
    np.asarray(single)
    out["fleet_single_device_compile_s"] = round(time.perf_counter() - t0, 1)
    progress({"ev": "phase", "name": "fleet_single_compile"})
    t_single = []
    for wi in range(2):
        t0 = time.perf_counter()
        np.asarray(ffd.ffd_solve_fused(inp, **kw))
        t_single.append((time.perf_counter() - t0) * 1e3)
        progress({"ev": "phase", "name": f"fleet_single_{wi}"})
    out["fleet_single_device_p50_ms"] = round(float(np.percentile(t_single, 50)), 1)
    # differential at the tier: sharded == unsharded, bit-for-bit
    np.testing.assert_array_equal(np.asarray(single), np.asarray(buf))
    out["fleet_sharded_equals_unsharded"] = True
    stage_fields(dict(out))

    out.update(_fleet_coalescing_gain(items, zones))
    return out


def _fleet_coalescing_gain(items, zones) -> dict:
    """N tenants x one coalescing sidecar: concurrent solves through the
    shared dispatch window vs the same solves sequential-isolated. The
    gain is overlap (device compute under one tenant's RTT serves
    another); on a 1-core CPU rig ~1.0 is the honest expectation."""
    import tempfile
    import threading

    from karpenter_tpu.apis import NodePool
    from karpenter_tpu.fleet.coalesce import DispatchCoalescer
    from karpenter_tpu.solver.rpc import SolverClient, SolverServer
    from karpenter_tpu.solver.service import TPUSolver

    n_tenants = _env_i("FLEET_TENANTS", 3)
    tenant_pods = _env_i("FLEET_TENANT_PODS", 5_000)
    pool = NodePool("default")
    workloads = [
        synth_pods(np.random.default_rng(9_000 + t), zones, tenant_pods, salt=t)
        for t in range(n_tenants)
    ]
    out: dict = {"fleet_tenants": n_tenants, "fleet_tenant_pods": tenant_pods}
    with tempfile.TemporaryDirectory(prefix="bench-fleet-") as d:
        sock = os.path.join(d, "fleet.sock")
        srv = SolverServer(path=sock, coalescer=DispatchCoalescer()).start()
        try:
            clients = [
                SolverClient(path=sock, tenant=f"bench-{t}", track_transport=False)
                for t in range(n_tenants)
            ]
            solvers = [
                TPUSolver(g_max=256, client=c, breaker=False) for c in clients
            ]
            # warm: stage + compile every tenant once
            for t in range(n_tenants):
                solvers[t].solve(pool, items, workloads[t])
            t0 = time.perf_counter()
            for t in range(n_tenants):
                solvers[t].solve(pool, items, workloads[t])
            sequential_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            threads = [
                threading.Thread(
                    target=solvers[t].solve, args=(pool, items, workloads[t])
                )
                for t in range(n_tenants)
            ]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            concurrent_s = time.perf_counter() - t0
            out["fleet_sequential_s"] = round(sequential_s, 2)
            out["fleet_coalesced_s"] = round(concurrent_s, 2)
            out["fleet_coalescing_gain"] = round(
                sequential_s / concurrent_s, 2
            ) if concurrent_s > 0 else 0.0
            for c in clients:
                c.close()
        finally:
            srv.stop()
    return out


def _mpod_stage(items, zones, progress=lambda ev: None,
                stage_fields=lambda fields: None, platform: str = "cpu") -> dict:
    """The 1M-pod / 5k-type MPOD tier (`make bench-mpod`): the
    million-pod tick on the multi-host 2x4 mesh layout with bit-packed
    masks end to end. Headline fields:

    - mpod_warm_tick_p50/p99_ms: mesh-sharded fused solve + fetch, warm,
      at 1M pods x 5k types with packed open/join masks on the
      host->device path;
    - mpod_mask_bytes_packed / _full_equiv / mpod_mask_reduction_x: the
      staged mask footprint packed vs the full bool [C, K] set, asserted
      >= 8x (the packing layer's contract at this tier);
    - mpod_packed_equals_full: the tier differential -- packed-mask and
      full-mask mesh solves produce bit-identical fused buffers;
    - mpod_ledger_reduction_x: the SAME >= 8x read back from a live
      TPUSolver HBM ledger (staged_bytes_by_kind) after a real solve, so
      the claim is pinned by the production accounting path, not a
      bench-side recomputation.

    Memory-aware skip below MPOD_MIN_AVAILABLE_GB (default 10): a
    million Pod objects plus the [C, K] float tier does not fit small
    rigs; the skip marker and the rig caveats persist through the
    side-file like every other field."""
    import jax

    from karpenter_tpu.fleet.shard import MeshSolveEngine
    from karpenter_tpu.parallel.mesh import make_mesh, make_mesh_2d
    from karpenter_tpu.solver import encode, ffd, packing

    cpu = platform == "cpu"
    # the CPU rig runs a scaled tier (same 5k-type K axis, fewer pods and
    # templates): a million-pod scan on one host core would blow the wall
    # budget without measuring anything the scaled tier does not -- the
    # full 1M x 5k tier is the accelerator capture's job
    n_pods = _env_i("MPOD_PODS", 1_000_000 if not cpu else 250_000)
    n_types = _env_i("MPOD_TYPES", 5_000)
    templates = _env_i("MPOD_TEMPLATES", 4_000 if not cpu else 1_000)
    g_default = 1_024 if not cpu else 128
    g_max = _env_i("MPOD_G_MAX", g_default)
    iters = _env_i("MPOD_ITERS", 3 if not cpu else 2)
    min_gib = _env_f("MPOD_MIN_AVAILABLE_GB", 10.0)
    out: dict = {
        "mpod_pods": n_pods, "mpod_types": n_types, "mpod_g_max": g_max,
        "rig_caveats": _rig_caveats(platform, g_max, 1_024),
    }
    if cpu and g_max < 1_024:
        out["mpod_g_max_capped_for_cpu"] = True
    if cpu and (n_pods < 1_000_000 or templates < 4_000):
        out["mpod_tier_scaled_for_cpu"] = True
        out["rig_caveats"].append(
            f"tier scaled to {n_pods // 1000}k pods / {templates} templates "
            "on the CPU rig; the accelerator capture (BENCH_MPOD_CAPTURE"
            ".json) runs the full 1M x 5k tier"
        )
    avail = _available_gib()
    if avail < min_gib:
        out["mpod_skipped"] = (
            f"memory-aware skip: {avail:.1f} GiB available < "
            f"{min_gib:.1f} GiB floor for the {n_pods // 1000}k-pod tier"
        )
        return out

    # the multi-host layout is the tier's point: 2 host rows x 4 devices
    # when the rig has them (DCN axis = hosts), else the 1-D fallback
    n_dev = min(8, len(jax.devices()))
    if n_dev >= 8:
        mesh = make_mesh_2d(2, 4)
        out["mpod_mesh_layout"] = "2x4"
    else:
        mesh = make_mesh(n_dev)
        out["mpod_mesh_layout"] = f"1d:{n_dev}"
    engine = MeshSolveEngine(mesh)
    out["mpod_mesh_devices"] = n_dev

    rng = np.random.default_rng(8484)
    t0 = time.perf_counter()
    pods = synth_fleet_pods(rng, zones, n_pods, templates)
    t_pods = time.perf_counter() - t0
    progress({"ev": "phase", "name": "mpod_synth", "secs": round(t_pods, 1)})
    t0 = time.perf_counter()
    classes = encode.group_pods(pods)
    cat = _fleet_catalog(items, n_types)
    cs = encode.encode_classes(
        classes, cat, c_pad=encode.bucket(len(classes), 16),
    )
    # a restrictive mask set (70% open / 90% join density): all-ones
    # masks would measure the packing but exercise no real bit traffic
    # through the kernels
    mrng = np.random.default_rng(515)
    cs.open_allowed = mrng.random((cs.c_pad, cat.k_pad)) < 0.7
    cs.join_allowed = mrng.random((cs.c_pad, cat.k_pad)) < 0.9
    out["mpod_classes"] = len(classes)
    out["mpod_encode_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
    out["mpod_synth_ms"] = round(t_pods * 1e3, 1)
    stage_fields(dict(out))
    progress({"ev": "phase", "name": "mpod_encode"})
    del pods

    staged, offsets, words = engine.stage_catalog(cat)
    inp_packed = ffd.make_inputs_staged(staged, cs, packed_masks=True)
    inp_full = ffd.make_inputs_staged(staged, cs)
    packed_b = packing.mask_nbytes(inp_packed.open_allowed) + \
        packing.mask_nbytes(inp_packed.join_allowed)
    full_b = packing.mask_nbytes(inp_full.open_allowed) + \
        packing.mask_nbytes(inp_full.join_allowed)
    out["mpod_mask_bytes_packed"] = int(packed_b)
    out["mpod_mask_bytes_full_equiv"] = int(full_b)
    ratio = full_b / max(packed_b, 1)
    out["mpod_mask_reduction_x"] = round(ratio, 2)
    assert ratio >= 8.0, (
        f"packed masks reduced staged bytes only {ratio:.2f}x at the "
        f"{n_types}-type tier (< the 8x contract)"
    )
    stage_fields(dict(out))

    nnz_max = ffd.nnz_budget(cs.c_pad, g_max)
    kw = dict(g_max=g_max, nnz_max=nnz_max, word_offsets=offsets, words=words)
    t0 = time.perf_counter()
    buf = engine.solve_fused(inp_packed, **kw)
    host = np.asarray(buf)
    out["mpod_compile_s"] = round(time.perf_counter() - t0, 1)
    out["mpod_unplaced_pods"] = int(host[2 : 2 + cs.c_pad].view(np.int32).sum())
    progress({"ev": "phase", "name": "mpod_compile", "secs": out["mpod_compile_s"]})
    ticks = []
    for wi in range(max(iters, 2)):
        t0 = time.perf_counter()
        buf = engine.solve_fused(inp_packed, **kw)
        np.asarray(buf)
        ticks.append((time.perf_counter() - t0) * 1e3)
        progress({"ev": "phase", "name": f"mpod_warm_{wi}"})
    out["mpod_warm_tick_p50_ms"] = round(float(np.percentile(ticks, 50)), 1)
    out["mpod_warm_tick_p99_ms"] = round(float(np.percentile(ticks, 99)), 1)
    stage_fields(dict(out))

    # tier differential: packed == full, bit-for-bit, on the mesh
    full_buf = np.asarray(engine.solve_fused(inp_full, **kw))
    np.testing.assert_array_equal(np.asarray(buf), full_buf)
    out["mpod_packed_equals_full"] = True
    stage_fields(dict(out))
    progress({"ev": "phase", "name": "mpod_differential"})

    # the production accounting path: a live solver's HBM ledger reports
    # the same reduction after a real packed-mask solve
    from karpenter_tpu.apis import NodePool
    from karpenter_tpu.solver.service import TPUSolver

    solver = TPUSolver(g_max=64, packed_masks=True)
    lpods = synth_pods(np.random.default_rng(99), zones, 2_000, salt=0)
    solver.solve(NodePool("default"), items, lpods)
    kinds = solver.staged_bytes_by_kind()
    lratio = kinds["class_masks_full_equiv"] / max(kinds["class_masks"], 1)
    out["mpod_ledger_mask_bytes"] = int(kinds["class_masks"])
    out["mpod_ledger_reduction_x"] = round(lratio, 2)
    assert lratio >= 8.0, (
        f"HBM ledger reports only {lratio:.2f}x packed-mask reduction "
        "(< the 8x contract)"
    )
    stage_fields(dict(out))
    return out


def _sim_scenario() -> dict:
    """Scenario-replay stage (sim subsystem): the medium diurnal scenario
    -- sustained sinusoidal arrivals, then a 30% pod churn -- replayed
    through the full operator stack on the in-process backend under
    FakeClock. The headline is replay THROUGHPUT (operator sweeps per
    wall-second, the capacity planning number for how fast policy changes
    can be judged against a scenario corpus) plus the fleet KPIs the
    scenario produces (cost-per-pod-hour, pending-latency p99, churn)."""
    from karpenter_tpu.sim.replay import replay
    from karpenter_tpu.sim.scenario import DEFAULT_SEED, build_scenario

    events = build_scenario("diurnal-medium", seed=DEFAULT_SEED)
    t0 = time.perf_counter()
    result = replay(events, backend="host", seed=DEFAULT_SEED)
    wall_s = time.perf_counter() - t0
    return {
        "sim_replay_ticks_per_s": round(result.ticks / wall_s, 2) if wall_s else 0.0,
        "sim_replay_wall_s": round(wall_s, 2),
        "sim_replay_ticks": result.ticks,
        "sim_replay_events": result.events_applied,
        "sim_scenario": "diurnal-medium",
        "sim_decision_digest": result.digest[:16],
        "sim_cost_per_pod_hour": result.kpis["cost_per_pod_hour"],
        "sim_pending_latency_p99_s": result.kpis["pending_latency_p99_s"],
        "sim_node_churn": result.kpis["node_churn"],
        "sim_pods": result.kpis["pods_total"],
    }


def _tunnel_rtt_ms(n: int = 5) -> float:
    """Median cost of synchronously fetching a fresh 32-byte device array:
    the tunnel's flat per-round-trip tax (~0 on a local chip)."""
    import jax
    import jax.numpy as jnp

    rtts = []
    for i in range(n):
        x = jnp.full((8,), i, jnp.uint32)
        jax.block_until_ready(x)
        t0 = time.perf_counter()
        np.asarray(x)
        rtts.append((time.perf_counter() - t0) * 1e3)
    return float(np.median(rtts))


def _gen2_collections() -> int:
    import gc

    return int(gc.get_stats()[2].get("collections", 0))


def run(profile: bool, progress=lambda ev: None, warm_only: bool = False,
        wire_only: bool = False, consolidate_only: bool = False,
        fleet_only: bool = False, mpod_only: bool = False,
        quality_only: bool = False, mesh_degrade_only: bool = False,
        convex_only: bool = False, coldstart_only: bool = False):
    import jax

    from karpenter_tpu.apis import NodePool
    from karpenter_tpu.solver.service import TPUSolver

    backend = jax.default_backend()
    progress({"ev": "backend", "backend": backend})

    # incremental headline persistence (satellite: r05 died rc=124 with
    # parsed null): every completed stage's fields stream out as a
    # stage_fields event; the parent folds them into the side-file partial
    # it rewrites after each event, so a hard `timeout -k` kill loses at
    # most the stage in flight, never the whole run
    acc: dict = {}

    def stage_fields(fields: dict) -> None:
        acc.update(fields)
        progress({"ev": "stage_fields", "fields": dict(acc)})
    # degraded-CPU runs measure a solve ~6x slower than the accelerator's;
    # trim iteration counts so the fallback stays bounded for the driver
    # (the percentiles remain meaningful, just coarser)
    iters = ITERS if backend != "cpu" else max(10, ITERS // 3)
    cold_iters = COLD_ITERS if backend != "cpu" else max(5, COLD_ITERS // 3)

    from karpenter_tpu.utils import enable_jax_compilation_cache

    enable_jax_compilation_cache()
    # jax retrace/transfer witness, installed BEFORE any solver work so
    # the compile-time breakdown covers the whole run (catalog staging,
    # bucket warms, adaptive warmup); the warm/wire stages then run their
    # measured loops inside hot() sections and persist the counters
    if os.environ.get("KARPENTER_TPU_JAX_WITNESS", "1") != "0":
        from karpenter_tpu.analysis import jax_witness

        jax_witness.install()
    t0 = time.perf_counter()
    items, cloud = build_catalog_items()
    zones = [z.name for z in cloud.describe_zones()]
    t_catalog = time.perf_counter() - t0
    progress({"ev": "phase", "name": "catalog", "secs": round(t_catalog, 2)})

    pool = NodePool("default")
    if warm_only:
        # `make bench-warm`: only the warm steady-state delta stage (plus
        # setup) -- the fast iteration loop for the incremental engine
        out = {
            "metric": f"warm_delta_tick_p50_{N_PODS // 1000}k_pods",
            "unit": "ms",
            "mode": "warm_delta_only",
            "platform": backend,
            "rig_caveats": _rig_caveats(backend, G_MAX, 1_024),
        }
        out.update(_warm_delta(pool, items, zones,
                               iters=10 if backend != "cpu" else 8))
        out["value"] = out.get("warm_delta_tick_p50_ms", 0.0)
        stage_fields(out)
        return out
    if wire_only:
        # `make bench-wire`: only the transport stage (plus setup) -- the
        # fast iteration loop for the wire-v2 layers
        out = {
            "metric": f"warm_wire_p50_{N_PODS // 1000}k_pods",
            "unit": "ms",
            "mode": "wire_only",
            "platform": backend,
            "rig_caveats": _rig_caveats(backend, G_MAX, 1_024),
        }
        out.update(_wire_stage(pool, items, zones,
                               iters=10 if backend != "cpu" else 6))
        out["value"] = out.get("warm_wire_p50_ms", 0.0)
        stage_fields(out)
        return out
    if fleet_only:
        # `make bench-fleet`: the 500k-pod / 2k-type mesh-sharded tier
        # (plus setup) -- sharded warm-tick p50/p99, the in-jit
        # all-gather's share, the multi-tenant coalescing gain; every
        # field streams through the side-file as it lands
        out = {
            "metric": f"fleet_warm_tick_p50_{_env_i('FLEET_PODS', 500_000) // 1000}k_pods",
            "unit": "ms",
            "mode": "fleet_only",
            "platform": backend,
        }
        stage_fields(dict(out))
        out.update(_fleet_stage(
            items, zones, progress=progress, stage_fields=stage_fields,
            platform=backend,
        ))
        out["value"] = out.get("fleet_warm_tick_p50_ms", 0.0)
        stage_fields(out)
        return out
    if mpod_only:
        # `make bench-mpod`: the 1M-pod / 5k-type multi-host tier (plus
        # setup) -- packed-mask mesh solve on the 2x4 layout, warm-tick
        # p50/p99, the >= 8x mask-byte reduction asserted against both
        # the staged inputs and the live HBM ledger, packed == full
        # differential; every field streams through the side-file
        out = {
            "metric": f"mpod_warm_tick_p50_{_env_i('MPOD_PODS', 1_000_000) // 1000}k_pods",
            "unit": "ms",
            "mode": "mpod_only",
            "platform": backend,
        }
        stage_fields(dict(out))
        out.update(_mpod_stage(
            items, zones, progress=progress, stage_fields=stage_fields,
            platform=backend,
        ))
        out["value"] = out.get("mpod_warm_tick_p50_ms", 0.0)
        stage_fields(out)
        return out
    if quality_only:
        # `make bench-quality`: only the solution-quality stage (plus
        # setup) -- the fast iteration loop for the quality observatory:
        # gap soundness + bound cost at the 10k/50k tiers
        out = {
            "metric": f"quality_gap_{min(N_PODS, 50_000) // 1000}k_pods",
            "unit": "ratio",
            "mode": "quality_only",
            "platform": backend,
            "rig_caveats": _rig_caveats(backend, G_MAX, 1_024),
        }
        out.update(_quality_stage(
            pool, items, zones, np.random.default_rng(42),
            iters=30 if backend != "cpu" else 12, platform=backend))
        out["value"] = out.get(
            f"quality_gap_{min(N_PODS, 50_000) // 1000}k", 0.0)
        stage_fields(out)
        return out
    if convex_only:
        # `make bench-convex`: only the convex-tier stage (plus setup)
        # -- the fast iteration loop for the global-solve tier: tick
        # cost vs FFD, gap after each tier, iterations to convergence
        out = {
            "metric": f"convex_tick_p50_{min(N_PODS, 50_000) // 1000}k_pods",
            "unit": "ms",
            "mode": "convex_only",
            "platform": backend,
            "rig_caveats": _rig_caveats(backend, G_MAX, 1_024),
        }
        out.update(_convex_stage(
            pool, items, zones, np.random.default_rng(42),
            iters=10 if backend != "cpu" else 5, platform=backend))
        out["value"] = out.get(
            f"convex_tick_p50_{min(N_PODS, 50_000) // 1000}k_ms", 0.0)
        stage_fields(out)
        return out
    if coldstart_only:
        # `make bench-coldstart`: only the coldstart stage (plus setup)
        # -- the fast iteration loop for the compile-cache subsystem:
        # first-tick latency cold vs warm-cache vs AOT-serialized in
        # fresh processes, restart-to-first-decision, the reshard first
        # tick with the degrade ladder precompiled, ladder overhead
        out = {
            "metric": "coldstart_aot_speedup_vs_cold",
            "unit": "x",
            "mode": "coldstart_only",
            "platform": backend,
            "rig_caveats": _rig_caveats(backend, G_MAX, 1_024),
        }
        out.update(_coldstart_stage(platform=backend, progress=progress))
        out["value"] = out.get("coldstart_aot_speedup_vs_cold", 0.0)
        stage_fields(out)
        return out
    if mesh_degrade_only:
        # `make bench-mesh-degrade`: only the mesh degrade stage (plus
        # setup) -- the fast iteration loop for the fault-tolerance
        # ladder: reshard p50/p99, the shrunk-layout warm-tick delta,
        # the quarantine-tick cost
        out = {
            "metric": "mesh_reshard_p50",
            "unit": "ms",
            "mode": "mesh_degrade_only",
            "platform": backend,
        }
        out.update(_mesh_degrade_stage(
            pool, items, zones, np.random.default_rng(42),
            iters=8 if backend != "cpu" else 5, platform=backend))
        out["value"] = out.get("mesh_reshard_p50_ms", 0.0)
        stage_fields(out)
        return out
    if consolidate_only:
        # `make bench-consolidate`: only the consolidation stage (plus
        # setup) -- the fast iteration loop for the disrupt engine
        out = {
            "metric": f"consolidation_nodes_per_s_{N_PODS // 1000}k_pods",
            "unit": "nodes/s",
            "mode": "consolidate_only",
            "platform": backend,
            "rig_caveats": _rig_caveats(backend, G_MAX, 1_024),
        }
        out.update(_consolidation_stage(
            pool, items, iters=8 if backend != "cpu" else 5))
        out["value"] = out.get("consolidation_nodes_per_s", 0.0)
        stage_fields(out)
        return out
    solver = TPUSolver(g_max=G_MAX)

    rng = np.random.default_rng(42)
    t0 = time.perf_counter()
    workloads = [synth_pods(rng, zones, N_PODS, salt) for salt in range(8)]
    t_pods = time.perf_counter() - t0
    progress({"ev": "phase", "name": "pods", "secs": round(t_pods, 2)})

    def solve(pods):
        return solver.solve(pool, items, pods)

    # first solves: compile + device staging + grouping-cache cold start.
    # Every workload is solved once so each distinct class-count bucket is
    # compiled before measurement begins.
    t0 = time.perf_counter()
    result = solve(workloads[0])
    t_compile = time.perf_counter() - t0
    progress({"ev": "phase", "name": "compile", "secs": round(t_compile, 2)})
    n_groups = len(result.new_groups)
    placed = sum(len(g.pods) for g in result.new_groups)
    assert placed + len(result.unschedulable) == N_PODS, "pod conservation violated"
    for w in workloads[1:]:
        solve(w)
    # precompile every class-count bucket: a cold workload whose pod mix
    # crosses a bucket boundary (e.g. 65 classes -> c_pad 128) would
    # otherwise hit a multi-second XLA compile inside a measured iteration
    # -- that was the whole of round 2's p99 tail
    t0 = time.perf_counter()
    # one bucket at a time so the watchdog sees a heartbeat per XLA
    # compile instead of one event after all seven
    for cp in TPUSolver.WARM_C_PADS:
        solver.warm(items, c_pads=(cp,))
        progress({"ev": "phase", "name": f"bucket_warm_{cp}"})
    t_warm_buckets = time.perf_counter() - t0

    # adaptive warmup: a tunneled chip's first seconds after idle can be
    # pathologically slow; warm until solve time stabilizes near its floor
    best = float("inf")
    stable = 0
    for wi in range(40):
        t0 = time.perf_counter()
        solve(workloads[0])
        dt = time.perf_counter() - t0
        progress({"ev": "phase", "name": f"warmup_{wi}"})
        if dt < best * 0.9:
            stable = 0
        elif dt <= best * 1.3:
            stable += 1
            if stable >= WARMUP:
                break
        else:
            stable = 0
        best = min(best, dt)

    # latency GC policy: freeze the warm baseline, stop gen2 collections
    # from firing inside measured ticks (the operator applies the same
    # policy at startup -- see utils.configure_gc_for_latency)
    from karpenter_tpu.utils import configure_gc_for_latency

    configure_gc_for_latency()
    gc2_start = _gen2_collections()
    rtt_before = _tunnel_rtt_ms()

    # cold pass FIRST (the HEADLINE): fresh Pod objects per iteration -- no
    # pod signature has ever been seen. Workload generation stays outside
    # the timer (pods arrive from watch events; creating them is not part
    # of the scheduling decision). Cold precedes warm so a mid-run tunnel
    # loss costs the secondary number, not the headline.
    cold = []
    for i in range(cold_iters):
        pods = synth_pods(rng, zones, N_PODS, salt=10_000 + i)
        g2 = _gen2_collections()
        t0 = time.perf_counter()
        solve(pods)
        ms = (time.perf_counter() - t0) * 1000.0
        cold.append(ms)
        progress({"ev": "cold_iter", "i": i, "ms": round(ms, 2),
                  "gc2": _gen2_collections() - g2})
    cold = np.array(cold)

    # warm pass: the 8 fixed workloads cycle, so grouping caches are hot
    warm = []
    for i in range(iters):
        pods = workloads[i % len(workloads)]
        g2 = _gen2_collections()
        t0 = time.perf_counter()
        solve(pods)
        ms = (time.perf_counter() - t0) * 1000.0
        warm.append(ms)
        progress({"ev": "warm_iter", "i": i, "ms": round(ms, 2),
                  "gc2": _gen2_collections() - g2})
    warm = np.array(warm)

    rtt_after = _tunnel_rtt_ms()
    gc2_total = _gen2_collections() - gc2_start

    p50, p99 = float(np.percentile(cold, 50)), float(np.percentile(cold, 99))
    warm_p50, warm_p99 = float(np.percentile(warm, 50)), float(np.percentile(warm, 99))
    stage_fields({
        "metric": f"p99_scheduling_decision_latency_{N_PODS // 1000}k_pods",
        "value": round(p99, 2), "unit": "ms",
        "vs_baseline": round(TARGET_MS / p99, 3) if p99 > 0 else 0.0,
        "p50_ms": round(p50, 2), "mode": "cold_pods",
        "warm_p50_ms": round(warm_p50, 2), "warm_p99_ms": round(warm_p99, 2),
        "platform": backend,
        "rig_caveats": _rig_caveats(backend, G_MAX, 1_024),
    })

    # fleet price of the decision under the price objective, and the same
    # workload solved with the legacy max-fit objective for the A/B
    # (VERDICT round 2, item 3: price drop at equal placement count)
    result = solve(workloads[0])
    fleet_price = sum(g.instance_types[0].cheapest_price() for g in result.new_groups)
    fit_solver = TPUSolver(g_max=G_MAX, objective="fit")
    fit_result = fit_solver.solve(pool, items, workloads[0])
    fit_placed = sum(len(g.pods) for g in fit_result.new_groups)
    fit_price = sum(g.instance_types[0].cheapest_price() for g in fit_result.new_groups)
    progress({"ev": "phase", "name": "fleet_ab"})

    stages, n_classes = _stage_breakdown(solver, pool, items, workloads[0])

    # the PRODUCTION sustained-tick number (round 6 headline field): K
    # back-to-back cold ticks through solve_begin/solve_finish, the same
    # two halves the provisioner's double-buffered tick drives by default.
    # Not a fenced secondary -- this is the production path's wall clock;
    # the try/except only protects the one-JSON-line contract.
    production: dict = {}
    k = 10 if backend != "cpu" else 4
    try:
        pipe = _pipelined_ticks(solver, pool, items, rng, zones, k=k, windows=3)
        production["production_tick_ms"] = round(float(np.median(pipe)), 2)
        production["production_tick_windows_ms"] = [round(x, 2) for x in pipe]
    except Exception as e:  # noqa: BLE001 - the JSON line must always appear
        production["production_tick_error"] = f"{type(e).__name__}: {e}"[:200]
    progress({"ev": "phase", "name": "production_pipelined"})
    stage_fields(production)

    # warm steady-state delta stage (the incremental-tick tentpole's
    # acceptance fields): always runs -- warm_delta_tick_p50_ms and the
    # delta-payload fields are headline acceptance data, not a secondary
    try:
        production.update(_warm_delta(
            pool, items, zones, iters=10 if backend != "cpu" else 8))
    except Exception as e:  # noqa: BLE001
        production["warm_delta_error"] = f"{type(e).__name__}: {e}"[:200]
    progress({"ev": "phase", "name": "warm_delta"})
    stage_fields(production)

    # wire transport stage (wire-v2 tentpole): ALWAYS runs --
    # warm_wire_p50/p99_ms, wire_share_of_tick, reply_bytes_per_solve and
    # the payload-copy counters are headline acceptance data
    try:
        production.update(_wire_stage(
            pool, items, zones, iters=10 if backend != "cpu" else 6))
    except Exception as e:  # noqa: BLE001
        production["wire_stage_error"] = f"{type(e).__name__}: {e}"[:200]
    progress({"ev": "phase", "name": "wire_transport"})
    stage_fields(production)

    # crash-recovery stage (crash-consistency tentpole): ALWAYS runs --
    # recovery_sweep_p50/p99_ms + the journal write overhead vs the warm
    # tick (<1% acceptance) are headline acceptance data, persisted via
    # the incremental side-file like every other stage
    try:
        production.update(_recovery_stage(
            warm_tick_p50_ms=production.get("warm_delta_tick_p50_ms"),
            iters=4 if backend != "cpu" else 3))
    except Exception as e:  # noqa: BLE001
        production["recovery_stage_error"] = f"{type(e).__name__}: {e}"[:200]
    progress({"ev": "phase", "name": "recovery"})
    stage_fields(production)

    # overload stage (overload-control tentpole): ALWAYS runs -- the
    # offered-load sweep (1x/3x/10x at the 10k tier) with the deadline
    # budget armed; overload_tick_p99_ms, shed_fraction and the
    # time-to-recover are headline acceptance data, persisted via the
    # incremental side-file like every other stage
    try:
        production.update(_overload_stage(
            iters_per_load=6 if backend != "cpu" else 4,
            tier_pods=min(N_PODS, 10_000)))
    except Exception as e:  # noqa: BLE001
        production["overload_stage_error"] = f"{type(e).__name__}: {e}"[:200]
    progress({"ev": "phase", "name": "overload"})
    stage_fields(production)

    # consolidation stage (device-consolidation tentpole): ALWAYS runs --
    # consolidation_nodes_per_s (>=100 at the 50k tier), sweep p50/p99,
    # and the device-vs-wire verdict differential (asserted 0) are
    # headline acceptance data, persisted via the incremental side-file
    try:
        production.update(_consolidation_stage(
            pool, items, iters=6 if backend != "cpu" else 4))
    except Exception as e:  # noqa: BLE001
        production["consolidation_stage_error"] = f"{type(e).__name__}: {e}"[:200]
    progress({"ev": "phase", "name": "consolidation"})
    stage_fields(production)

    # solution-quality stage (quality-observatory tentpole): ALWAYS runs
    # -- gap >= 1.0 at the 10k/50k tiers, the bound's own dispatch+fetch
    # cost vs the warm tick (<1% acceptance), and the witness counters
    # for the bound's measured loop are headline acceptance data,
    # persisted via the incremental side-file like every other stage
    try:
        production.update(_quality_stage(
            pool, items, zones, rng,
            warm_tick_p50_ms=production.get("warm_delta_tick_p50_ms") or warm_p50,
            iters=30 if backend != "cpu" else 12, platform=backend))
    except Exception as e:  # noqa: BLE001
        production["quality_stage_error"] = f"{type(e).__name__}: {e}"[:200]
    progress({"ev": "phase", "name": "quality"})
    stage_fields(production)

    # convex-tier stage (global-solve tentpole): ALWAYS runs -- the
    # convex tick's cost vs FFD at the 10k/50k tiers, the gap under
    # each tier, iterations to convergence, and the end-to-end
    # never-worse assertion are headline acceptance data, persisted
    # via the incremental side-file like every other stage
    try:
        production.update(_convex_stage(
            pool, items, zones, rng,
            iters=10 if backend != "cpu" else 5, platform=backend))
    except Exception as e:  # noqa: BLE001
        production["convex_stage_error"] = f"{type(e).__name__}: {e}"[:200]
    progress({"ev": "phase", "name": "convex"})
    stage_fields(production)

    # mesh degrade stage (mesh fault-tolerance tentpole): ALWAYS runs --
    # reshard p50/p99, the shrunk-layout warm-tick delta vs the full
    # mesh, and the quarantine-tick cost are headline acceptance data,
    # persisted via the incremental side-file like every other stage
    try:
        production.update(_mesh_degrade_stage(
            pool, items, zones, rng,
            iters=8 if backend != "cpu" else 5, platform=backend))
    except Exception as e:  # noqa: BLE001
        production["mesh_degrade_stage_error"] = f"{type(e).__name__}: {e}"[:200]
    progress({"ev": "phase", "name": "mesh_degrade"})
    stage_fields(production)

    # coldstart stage (zero-compile cold-start tentpole): ALWAYS runs --
    # cold vs warm-cache vs AOT-serialized first-tick latency in fresh
    # processes, restart-to-first-decision, the reshard-first-tick delta
    # with the degrade ladder precompiled, and the warmup ladder's
    # steady-state overhead are headline acceptance data, persisted via
    # the incremental side-file like every other stage
    try:
        production.update(_coldstart_stage(platform=backend, progress=progress))
    except Exception as e:  # noqa: BLE001
        production["coldstart_stage_error"] = f"{type(e).__name__}: {e}"[:200]
    progress({"ev": "phase", "name": "coldstart"})
    stage_fields(production)

    # secondary measurements -- each individually fenced so a failure can
    # never cost the headline (the JSON line must always appear)
    secondary: dict = {}
    if os.environ.get("BENCH_SKIP_SECONDARY") != "1":
        try:
            secondary["rpc_loopback_p50_ms"] = round(
                _rpc_loopback_p50(pool, items, workloads,
                                  iters=6 if backend != "cpu" else 3), 2)
        except Exception as e:  # noqa: BLE001
            secondary["rpc_loopback_error"] = f"{type(e).__name__}: {e}"[:200]
        progress({"ev": "phase", "name": "rpc_loopback"})
        stage_fields(secondary)
        try:
            secondary.update(_mixed_affinity(
                solver, pool, items, zones, rng,
                iters=5 if backend != "cpu" else 2))
        except Exception as e:  # noqa: BLE001
            secondary["mixed_affinity_error"] = f"{type(e).__name__}: {e}"[:200]
        progress({"ev": "phase", "name": "mixed_affinity"})
        stage_fields(secondary)
        # stage-attributed tracing segment (observability PR): per-span
        # p50/p99 through the production rig topology + overlap fraction,
        # and the measured tracing tax on this tier's solve
        try:
            secondary.update(_traced_rig(min(N_PODS, 10_000)))
        except Exception as e:  # noqa: BLE001
            secondary["trace_rig_error"] = f"{type(e).__name__}: {e}"[:200]
        progress({"ev": "phase", "name": "traced_rig"})
        stage_fields(secondary)
        try:
            secondary.update(_tracing_overhead(
                solver, pool, items, workloads,
                iters=8 if backend != "cpu" else 4))
        except Exception as e:  # noqa: BLE001
            secondary["tracing_overhead_error"] = f"{type(e).__name__}: {e}"[:200]
        progress({"ev": "phase", "name": "tracing_overhead"})
        stage_fields(secondary)
        # observatory overhead (device-observatory PR): the per-tick
        # flight-record + HBM-poll + staged-bytes cost, measured the same
        # direct way as the tracing tax and asserted <1% of the tick
        try:
            secondary.update(_observatory_overhead(
                solver, secondary.get("tracing_off_p50_ms", 0.0)))
        except Exception as e:  # noqa: BLE001
            secondary["observatory_overhead_error"] = f"{type(e).__name__}: {e}"[:200]
        progress({"ev": "phase", "name": "observatory_overhead"})
        stage_fields(secondary)
        # degraded-mode stage (robustness PR): sidecar down + breaker open
        # -> breaker_open_tick_p99_ms proves the tick completes on the CPU
        # fallback with no connect stall
        try:
            secondary.update(_breaker_degraded(
                pool, items, zones, rng,
                iters=8 if backend != "cpu" else 4))
        except Exception as e:  # noqa: BLE001
            secondary["breaker_degraded_error"] = f"{type(e).__name__}: {e}"[:200]
        progress({"ev": "phase", "name": "breaker_degraded"})
        stage_fields(secondary)
        # scenario-replay stage (sim subsystem): ticks/s through the full
        # operator stack on the medium diurnal scenario + its fleet KPIs
        try:
            secondary.update(_sim_scenario())
        except Exception as e:  # noqa: BLE001
            secondary["sim_replay_error"] = f"{type(e).__name__}: {e}"[:200]
        progress({"ev": "phase", "name": "sim_scenario"})
        stage_fields(secondary)

    # decompose the wall-clock number into tunnel overhead vs compute.
    # Under axon the chip sits behind a network tunnel whose EVERY
    # synchronous host<->device round trip costs a flat ~64 ms regardless
    # of payload (a 32-byte fetch and a 120 KB fetch both measure ~64 ms);
    # the solve pays exactly ONE such round trip. On a real TPU VM -- the
    # deployment the solver targets (SURVEY.md section 2.4) -- that term
    # is ~0. tunnel_rtt_ms: median of the before/after cold-pass samples.
    # device_exec_ms: (dispatch+sync of the solve) minus the round trip --
    # the chip's actual compute. compute_sum_ms: host stages + device
    # compute, i.e. the latency with no tunnel.
    tunnel_rtt = float(np.median([rtt_before, rtt_after]))
    device_exec = max(0.0, stages["solve_fetch"] - tunnel_rtt)
    compute_sum = (
        stages["group"] + stages["encode"] + device_exec + stages["decode"]
    )

    if profile:
        print(
            f"# backend {backend}; catalog build {t_catalog * 1e3:.0f}ms; "
            f"pod synth {t_pods:.1f}s; first solve (compile) {t_compile:.1f}s; "
            f"bucket warm {t_warm_buckets:.1f}s; "
            f"cold p50 {p50:.1f}ms p99 {p99:.1f}ms min {cold.min():.1f}ms max {cold.max():.1f}ms; "
            f"warm p50 {warm_p50:.1f}ms p99 {warm_p99:.1f}ms max {warm.max():.1f}ms; "
            f"gen2 GCs during measurement: {gc2_total}; "
            f"stages (warm, serial) {stages} ({n_classes} classes); "
            f"tunnel rtt {rtt_before:.1f}/{rtt_after:.1f}ms (before/after cold) "
            f"-> device exec ~{device_exec:.1f}ms, "
            f"compute sum (no tunnel) ~{compute_sum:.1f}ms; "
            f"groups opened {n_groups}; pods placed {placed}/{N_PODS}; "
            f"fleet price ${fleet_price:.2f}/h (max-fit objective: ${fit_price:.2f}/h, "
            f"{fit_placed} placed)",
            file=sys.stderr,
        )

    k_real = solver.catalog_tensors(items).k_real
    return {
        "metric": f"p99_scheduling_decision_latency_{N_PODS // 1000}k_pods_{k_real}_types",
        "value": round(p99, 2),
        "unit": "ms",
        "vs_baseline": round(TARGET_MS / p99, 3) if p99 > 0 else 0.0,
        "p50_ms": round(p50, 2),
        "mode": "cold_pods",
        "warm_p50_ms": round(warm_p50, 2),
        "warm_p99_ms": round(warm_p99, 2),
        "tail_ratio_p99_p50": round(p99 / p50, 3) if p50 > 0 else 0.0,
        "cold_iters_ms": [round(x, 1) for x in cold.tolist()],
        "warm_iters_ms": [round(x, 1) for x in warm.tolist()],
        "gc_gen2_during_measurement": gc2_total,
        "stages_ms": stages,
        "tunnel_rtt_ms": round(tunnel_rtt, 2),
        "tunnel_rtt_before_after_ms": [round(rtt_before, 2), round(rtt_after, 2)],
        "device_exec_ms_est": round(device_exec, 2),
        "compute_sum_ms": round(compute_sum, 2),
        "platform": backend,
        "groups_opened": n_groups,
        "pods_placed": placed,
        "fleet_price_per_hour": round(fleet_price, 2),
        "fleet_price_fit_mode": round(fit_price, 2),
        "objective": solver.objective,
        "rig_caveats": _rig_caveats(backend, G_MAX, 1_024),
        **production,
        **secondary,
    }


# -- coldstart stage (zero-compile cold-start tentpole) ---------------------
def _coldstart_child() -> None:
    """One coldstart measurement process (spawned by _coldstart_stage with
    ``--coldstart-child MODE --coldstart-dir DIR``): build the catalog +
    a fixed deterministic workload, measure the FIRST production solve of
    this process under the jax witness, print one JSON line. Modes share
    DIR (the versioned compile-cache root), so the sequence cold -> warm
    -> aot is exactly the operator restart story: cold pays the full
    trace+compile storm then populates both cache layers; warm restarts
    onto the persistent XLA cache; aot restarts onto deserialized
    executables. ``reshard`` is the mesh chapter: warm the degrade
    ladder's shrunk layouts via the AOT plan, quarantine a device, and
    measure the first tick on the shrunk layout."""
    mode = sys.argv[sys.argv.index("--coldstart-child") + 1]
    cache_dir = sys.argv[sys.argv.index("--coldstart-dir") + 1]
    t0_env = float(os.environ.get("COLDSTART_T0", time.time()))

    if os.environ.get("BENCH_FORCE_CPU") == "1":
        import jax

        jax.config.update("jax_platforms", "cpu")
    from karpenter_tpu.analysis import jax_witness
    from karpenter_tpu.apis import NodePool
    from karpenter_tpu.obs import jitstats
    from karpenter_tpu.solver.service import TPUSolver
    from karpenter_tpu.utils import enable_jax_compilation_cache

    jax_witness.install()
    home = enable_jax_compilation_cache(cache_dir)
    out: dict = {"mode": mode, "ok": True}

    items, cloud = build_catalog_items()
    zones = [z.name for z in cloud.describe_zones()]
    # sized so the compile storm DOMINATES the cold tick (the quantity
    # this stage isolates): host-side encode scales with pods while
    # compile time is flat, so a large workload buries the cache win
    # under a floor every mode pays identically
    n_pods = _env_i("COLDSTART_PODS", 1_200)
    pods = synth_pods(np.random.default_rng(77), zones, n_pods,
                      salt=77, templates=_env_i("COLDSTART_TEMPLATES", 24))
    pool = NodePool("default")
    exec_dir = os.path.join(home, "exec") if home else None

    def decisions_sig(result) -> str:
        import hashlib

        doc = sorted(
            (sorted(it.name for it in g.instance_types),
             sorted(p.metadata.name for p in g.pods))
            for g in result.new_groups
        )
        return hashlib.sha256(json.dumps(doc).encode()).hexdigest()[:16]

    def first_tick(solver):
        # the catalog stages when the watch delivers it -- BEFORE pending
        # pods arrive -- so the first decision tick dispatches onto staged
        # tensors in every mode; staging cost is reported on its own and
        # restart_to_first_decision_ms still covers everything
        t0 = time.perf_counter()
        solver._catalog(items)
        out["catalog_stage_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
        st0 = jax_witness.stats()
        t0 = time.perf_counter()
        with jax_witness.hot("coldstart-first-tick"):
            result = solver.solve(pool, items, pods)
        dt = (time.perf_counter() - t0) * 1e3
        st1 = jax_witness.stats()
        out.update(
            first_tick_ms=round(dt, 2),
            first_tick_compiles=int(
                st1["compiles_total"] - st0["compiles_total"]),
            first_tick_compile_ms=round(
                (st1["compile_secs_total"] - st0["compile_secs_total"]) * 1e3, 1),
            first_tick_traces=int(st1["traces_total"] - st0["traces_total"]),
            restart_to_first_decision_ms=round((time.time() - t0_env) * 1e3, 1),
            decisions=decisions_sig(result),
        )
        return result

    if mode == "reshard":
        import jax

        n_dev = 1
        for p in (8, 4, 2):
            if len(jax.devices()) >= p:
                n_dev = p
                break
        if n_dev < 2:
            out.update(ok=False, skipped=f"{len(jax.devices())} device(s)")
            print(json.dumps(out))
            return
        solver = TPUSolver(g_max=128, mesh=n_dev)
        mgr = solver.enable_aot(None, serialize=False, duty=1.0)
        r0 = solver.solve(pool, items, pods)   # full-mesh compile + stage
        out["decisions"] = decisions_sig(r0)
        # arm the degrade ladder's shrunk layouts BEFORE any loss: the
        # whole point of the AOT mesh tier
        mgr.run_plan(solver._catalog(items), throttle=False)
        warm = []
        for _ in range(5):
            t0 = time.perf_counter()
            solver.solve(pool, items, pods)
            warm.append((time.perf_counter() - t0) * 1e3)
        out["full_warm_p50_ms"] = round(float(np.percentile(warm, 50)), 2)
        solver.mesh_engine.quarantine_worst_device("coldstart-bench")
        st0 = jax_witness.stats()
        t0 = time.perf_counter()
        with jax_witness.hot("coldstart-reshard-tick"):
            r1 = solver.solve(pool, items, pods)
        st1 = jax_witness.stats()
        out.update(
            reshard_first_tick_ms=round((time.perf_counter() - t0) * 1e3, 2),
            reshard_first_tick_compiles=int(
                st1["compiles_total"] - st0["compiles_total"]),
            reshard_first_tick_traces=int(
                st1["traces_total"] - st0["traces_total"]),
            reshard_decisions_identical=decisions_sig(r1) == out["decisions"],
        )
        print(json.dumps(out))
        return

    solver = TPUSolver(g_max=128)
    if mode == "aot":
        mgr = solver.enable_aot(exec_dir, serialize=True, duty=1.0)
        out["loaded"] = solver.describe_aot().get("loaded", 0)
    first_tick(solver)
    cs = jitstats.cache_stats()
    out.update(cache_hits=int(cs["hits"]), cache_misses=int(cs["misses"]))

    if mode == "cold":
        # capture the pad the production dispatch actually used (the
        # bound's `placed` vector is zeros[c_pad]) so the AOT plan
        # compiles exactly the hot bucket, then build + serialize it
        # synchronously -- the artifact set the warm/aot modes restart on
        pad_cell: list = []
        orig = solver._dispatch_bound

        def _cap(inp, placed, *a, **kw):
            pad_cell.append(int(placed.shape[0]))
            return orig(inp, placed, *a, **kw)

        solver._dispatch_bound = _cap
        try:
            solver.solve(pool, items, pods)
        finally:
            solver._dispatch_bound = orig
        pad = pad_cell[0] if pad_cell else 64
        out["pad"] = pad
        mgr = solver.enable_aot(exec_dir, serialize=True, duty=1.0,
                                pads=(pad,))
        t0 = time.perf_counter()
        plan = mgr.run_plan(solver._catalog(items), throttle=False)
        out["plan_tasks"] = plan["tasks"]
        out["plan_compiled"] = plan["compiled"]
        out["plan_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
        out["store"] = solver.describe_aot().get("store", {})
        out["cache_bytes"] = int(jitstats.update_cache_bytes(home)) if home else 0
    elif mode == "aot":
        from karpenter_tpu import metrics as metrics_mod

        out["aot_dispatches"] = int(sum(
            metrics_mod.REGISTRY.counter(
                "karpenter_aot_dispatches_total", "", ("entry",)
            ).value(entry=e)
            for e in ("ffd_solve_fused", "fractional_price_bound")))

        def tick_ms(s) -> float:
            t0 = time.perf_counter()
            s.solve(pool, items, pods)
            return (time.perf_counter() - t0) * 1e3

        # Steady-state ladder overhead (<1% contract): the per-dispatch
        # cost of the armed AOT rungs themselves -- exec-key lookup +
        # Compiled call vs the plain jit dispatch.  A pure-JIT solver in
        # the SAME process reuses the module-level compiled entries, so
        # the pair isolates the dispatch path; ticks are INTERLEAVED
        # A/B/A/B because same-process throughput drifts monotonically
        # (allocator warmup) and back-to-back batches would charge that
        # drift to whichever solver ran first.
        mgr.drain(timeout_s=60)
        jit_solver = TPUSolver(g_max=128)   # same tier as the armed solver
        jit_solver.solve(pool, items, pods)  # warm host-side + jit caches
        armed_xs, jit_xs = [], []
        for _ in range(9):
            armed_xs.append(tick_ms(solver))
            jit_xs.append(tick_ms(jit_solver))
        idle = float(np.percentile(armed_xs, 50))
        pure = float(np.percentile(jit_xs, 50))
        # Re-warm burst: full plan re-run at the production duty cycle
        # while ticking.  Reported separately -- on the CPU rig the
        # background compiles contend for the GIL with the tick, so this
        # transient is an upper bound, not the steady-state number.
        mgr.duty = float(os.environ.get("KARPENTER_TPU_AOT_DUTY", "0.05"))
        mgr.on_catalog(solver._catalog(items))
        busy = float(np.percentile([tick_ms(solver) for _ in range(7)], 50))
        mgr.drain(timeout_s=300)
        out.update(
            ladder_idle_p50_ms=round(idle, 2),
            jit_p50_ms=round(pure, 2),
            ladder_busy_p50_ms=round(busy, 2),
            ladder_overhead_frac=round(max(0.0, idle / pure - 1.0), 4)
            if pure > 0 else 0.0,
            ladder_rewarm_frac=round(max(0.0, busy / idle - 1.0), 4)
            if idle > 0 else 0.0,
        )
    print(json.dumps(out))


def _coldstart_stage(platform: str = "cpu", progress=lambda ev: None) -> dict:
    """Coldstart stage (zero-compile cold-start tentpole): ALWAYS runs.
    First-tick latency measured in FRESH processes sharing one compile
    cache -- the operator restart story end to end:

    - coldstart_cold_first_tick_ms: empty cache, the full trace+compile
      storm (the child then builds + serializes the AOT plan, populating
      both cache layers for the later modes);
    - coldstart_warm_first_tick_ms: persistent XLA cache only (the
      sidecar restart path -- compiles become cache loads);
    - coldstart_aot_first_tick_ms: deserialized executables armed before
      the first catalog (the operator restart path -- zero compiles),
      plus restart-to-first-decision wall time and the steady-state
      warmup-ladder overhead vs the <1% contract;
    - coldstart_reshard_first_tick_ms: mesh chapter -- shrunk layouts
      precompiled by the ladder, first tick after a quarantine.
    """
    import shutil
    import subprocess
    import tempfile

    cache_dir = tempfile.mkdtemp(prefix="bench_coldstart_cache_")
    budget = _env_f("BENCH_COLDSTART_CHILD_BUDGET_S", 900.0)
    out: dict = {"coldstart_pods": _env_i("COLDSTART_PODS", 1_200)}
    children: dict = {}
    try:
        for mode in ("cold", "warm", "aot", "reshard"):
            env = dict(
                os.environ, COLDSTART_T0=str(time.time()),
                KARPENTER_TPU_COMPILE_CACHE=cache_dir,
            )
            # fresh-process measurement: the parent's progress plumbing
            # must not leak in (the child prints its own one JSON line)
            env.pop("BENCH_PROGRESS_PATH", None)
            if mode == "reshard" and platform == "cpu":
                env["XLA_FLAGS"] = (
                    env.get("XLA_FLAGS", "")
                    + " --xla_force_host_platform_device_count=8"
                ).strip()
            try:
                proc = subprocess.run(
                    [sys.executable, os.path.abspath(__file__),
                     "--coldstart-child", mode, "--coldstart-dir", cache_dir],
                    capture_output=True, text=True, timeout=budget, env=env,
                )
                line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else "{}"
                doc = json.loads(line)
                if proc.returncode != 0 or not doc.get("ok", False):
                    raise RuntimeError(
                        doc.get("skipped")
                        or f"rc={proc.returncode}: {proc.stderr[-300:]}")
                children[mode] = doc
            except Exception as e:  # noqa: BLE001 -- each mode fenced: a
                # failed child costs its fields, never the stage
                out[f"coldstart_{mode}_error"] = f"{type(e).__name__}: {e}"[:300]
            progress({"ev": "phase", "name": f"coldstart_{mode}"})
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)

    cold, warm, aot = (children.get(m) for m in ("cold", "warm", "aot"))
    if cold:
        out.update(
            coldstart_cold_first_tick_ms=cold["first_tick_ms"],
            coldstart_cold_compile_ms=cold["first_tick_compile_ms"],
            coldstart_cold_restart_to_first_decision_ms=cold[
                "restart_to_first_decision_ms"],
            coldstart_pad=cold.get("pad"),
            coldstart_catalog_stage_ms=cold.get("catalog_stage_ms"),
            coldstart_store_artifacts=cold.get("store", {}).get("artifacts"),
            coldstart_store_bytes=cold.get("store", {}).get("bytes"),
            coldstart_cache_bytes=cold.get("cache_bytes"),
        )
    if warm:
        out["coldstart_warm_first_tick_ms"] = warm["first_tick_ms"]
        out["coldstart_warm_cache_misses"] = warm["cache_misses"]
        out["coldstart_warm_first_tick_compiles"] = warm["first_tick_compiles"]
    if aot:
        out.update(
            coldstart_aot_first_tick_ms=aot["first_tick_ms"],
            coldstart_aot_first_tick_compiles=aot["first_tick_compiles"],
            coldstart_aot_first_tick_traces=aot["first_tick_traces"],
            coldstart_aot_cache_misses=aot["cache_misses"],
            coldstart_aot_loaded=aot.get("loaded"),
            coldstart_restart_to_first_decision_ms=aot[
                "restart_to_first_decision_ms"],
            coldstart_ladder_idle_p50_ms=aot.get("ladder_idle_p50_ms"),
            coldstart_jit_p50_ms=aot.get("jit_p50_ms"),
            coldstart_ladder_busy_p50_ms=aot.get("ladder_busy_p50_ms"),
            coldstart_ladder_overhead_frac=aot.get("ladder_overhead_frac"),
            coldstart_ladder_rewarm_frac=aot.get("ladder_rewarm_frac"),
        )
    if cold and warm and cold["first_tick_ms"] > 0 and warm["first_tick_ms"] > 0:
        out["coldstart_warm_speedup_vs_cold"] = round(
            cold["first_tick_ms"] / warm["first_tick_ms"], 2)
    if cold and aot and aot["first_tick_ms"] > 0:
        out["coldstart_aot_speedup_vs_cold"] = round(
            cold["first_tick_ms"] / aot["first_tick_ms"], 2)
    sigs = {m: d.get("decisions") for m, d in children.items() if d.get("decisions")}
    if len(sigs) >= 2:
        base = sigs.get("cold") or next(iter(sigs.values()))
        # the AOT differential, end to end: every cache layer must leave
        # the DECISION bit-identical (mesh mode packs under a different
        # g_max tier, so `reshard` asserts against its own full-mesh tick)
        out["coldstart_decisions_identical"] = all(
            v == base for m, v in sigs.items() if m != "reshard")
    reshard = children.get("reshard")
    if reshard and "reshard_first_tick_ms" in reshard:
        out.update(
            coldstart_reshard_first_tick_ms=reshard["reshard_first_tick_ms"],
            coldstart_reshard_first_tick_compiles=reshard[
                "reshard_first_tick_compiles"],
            coldstart_reshard_decisions_identical=reshard.get(
                "reshard_decisions_identical"),
        )
        if reshard.get("full_warm_p50_ms", 0) > 0:
            out["coldstart_reshard_tick_over_warm"] = round(
                reshard["reshard_first_tick_ms"] / reshard["full_warm_p50_ms"], 2)
    return out


# -- child ------------------------------------------------------------------
def _child_main() -> None:
    profile = "--profile" in sys.argv
    path = os.environ.get("BENCH_PROGRESS_PATH")
    f = open(path, "a", buffering=1) if path else None

    def progress(ev):
        if f is not None:
            f.write(json.dumps(ev) + "\n")

    if os.environ.get("BENCH_FORCE_CPU") == "1":
        import jax

        # the environment may pin JAX_PLATFORMS to a remote-accelerator
        # plugin via sitecustomize; the config override wins regardless
        jax.config.update("jax_platforms", "cpu")
    try:
        out = run(profile, progress, warm_only="--warm-only" in sys.argv,
                  wire_only="--wire-only" in sys.argv,
                  consolidate_only="--consolidate-only" in sys.argv,
                  fleet_only="--fleet-only" in sys.argv,
                  mpod_only="--mpod-only" in sys.argv,
                  quality_only="--quality-only" in sys.argv,
                  mesh_degrade_only="--mesh-degrade-only" in sys.argv,
                  convex_only="--convex-only" in sys.argv,
                  coldstart_only="--coldstart-only" in sys.argv)
        progress({"ev": "result", "out": out})
        print(json.dumps(out))
    except Exception as e:  # noqa: BLE001 - parent assembles a partial
        traceback.print_exc()
        progress({"ev": "error", "error": f"{type(e).__name__}: {e}"[:300]})
        sys.exit(3)


# -- parent -----------------------------------------------------------------
# live state for the SIGTERM last-resort: the watch loop records the
# running child and its progress path here (and main records the degrade
# transition) so the handler can kill the child, assemble the best
# partial WITH its claim provenance, and still print the one JSON line
_WATCH = {
    "proc": None, "events_path": None, "degraded": False, "probe_error": None,
    # incremental persistence (satellite: r05 rc=124, parsed null): the
    # watch loop rewrites this side file (write-then-rename) with the best
    # current partial after every progress event, so the SIGTERM handler
    # only has to FLUSH it -- and even a straight SIGKILL leaves it on
    # disk for post-mortem
    "side_path": None,
}


def _write_side(out: dict) -> None:
    """Atomically persist the current best partial to the side file."""
    side = _WATCH.get("side_path")
    if not side or out is None:
        return
    try:
        tmp = side + ".tmp"
        with open(tmp, "w") as f:
            json.dump(out, f)
        os.replace(tmp, side)
    except OSError:
        pass  # persistence is best-effort; the events path still exists


def _read_side() -> "dict | None":
    side = _WATCH.get("side_path")
    if not side:
        return None
    try:
        with open(side) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _clamped_budget(env_name: str, default: float, remaining: float, reserve: float) -> float:
    """A stage budget (probe, accelerator child, CPU child) may never
    exceed what is left of the wall budget minus a reserve for the stages
    after it -- round 5's artifact was lost to a probe whose own default
    budget exceeded the DRIVER's timeout, so the driver SIGKILLed before
    the always-print-one-line contract fired (BENCH_r05: rc 124,
    parsed null)."""
    return max(0.0, min(_env_f(env_name, default), remaining - reserve))


def _install_sigterm_last_resort() -> None:
    """Last line of defense for the one-JSON-line contract: on SIGTERM,
    kill the child, assemble the best partial from its progress events,
    and print the line before exiting 0."""
    import signal

    def _on_term(signum, frame):  # noqa: ARG001
        proc = _WATCH.get("proc")
        if proc is not None:
            try:
                proc.kill()
            except OSError:
                pass
        # fast path: the watch loop has been persisting the best partial
        # incrementally; flushing it needs no event re-parse, so the line
        # lands inside even a short `timeout -k` grace window
        out = _read_side()
        if out is None:
            events = _read_events(_WATCH["events_path"]) if _WATCH.get("events_path") else []
            out = _assemble_partial(events, f"terminated by signal {signum}")
        if out is None:
            out = {
                "metric": f"p99_scheduling_decision_latency_{N_PODS // 1000}k_pods",
                "value": 0.0,
                "unit": "ms",
                "vs_baseline": 0.0,
                "error": f"terminated by signal {signum} before any usable iterations",
                "degraded": True,
            }
            _attach_capture(out)
        else:
            out["partial_reason"] = f"terminated by signal {signum}"
            if _WATCH.get("degraded"):
                # same provenance contract as the normal CPU-fallback exit:
                # a degraded partial must say so and carry the committed
                # TPU capture as the accelerator claim's basis
                out["degraded"] = True
                out["probe_error"] = (_WATCH.get("probe_error") or "")[:300]
                out.setdefault("claim_basis", "cpu_degraded")
                _attach_capture(out)
        print(json.dumps(out))
        sys.stdout.flush()
        os._exit(0)

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except ValueError:
        pass  # not the main thread (embedded use): no handler, no harm


def _read_events(path: str) -> list:
    events = []
    try:
        with open(path) as f:
            for line in f:
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    pass
    except OSError:
        pass
    return events


def _run_child(force_cpu: bool, profile: bool, budget_s: float, stall_s: float):
    """Run the measurement child, watching its progress file. Returns
    (result_dict_or_None, events, why_stopped)."""
    import subprocess
    import tempfile

    fd, path = tempfile.mkstemp(prefix="bench_progress_", suffix=".jsonl")
    os.close(fd)
    env = dict(os.environ, BENCH_PROGRESS_PATH=path)
    if force_cpu:
        env["BENCH_FORCE_CPU"] = "1"
    args = [sys.executable, os.path.abspath(__file__), "--child"]
    if profile:
        args.append("--profile")
    if "--warm-only" in sys.argv:
        args.append("--warm-only")
    if "--wire-only" in sys.argv:
        args.append("--wire-only")
    if "--consolidate-only" in sys.argv:
        args.append("--consolidate-only")
    if "--fleet-only" in sys.argv:
        args.append("--fleet-only")
    if "--mpod-only" in sys.argv:
        args.append("--mpod-only")
    if "--quality-only" in sys.argv:
        args.append("--quality-only")
    if "--mesh-degrade-only" in sys.argv:
        args.append("--mesh-degrade-only")
    if "--convex-only" in sys.argv:
        args.append("--convex-only")
    if "--coldstart-only" in sys.argv:
        args.append("--coldstart-only")
    proc = subprocess.Popen(
        args, stdout=subprocess.DEVNULL, stderr=None, text=True, env=env
    )
    _WATCH["proc"], _WATCH["events_path"] = proc, path
    start = time.monotonic()
    last_size = -1
    last_change = start
    last_side = 0.0
    side_dirty = False
    measuring = False
    # single long operations before the first measured iteration (the
    # first XLA compile of a 50k-pod program over a cold tunnel, a slow
    # catalog stage) legitimately emit nothing for minutes -- give the
    # startup phases a longer leash than the per-iteration cadence
    startup_stall = max(stall_s, _env_f("BENCH_STARTUP_STALL_S", 900))
    why = ""
    while True:
        rc = proc.poll()
        if rc is not None:
            why = "" if rc == 0 else f"child exited rc={rc}"
            break
        now = time.monotonic()
        try:
            size = os.path.getsize(path)
        except OSError:
            size = 0
        if size != last_size:
            last_size = size
            last_change = now
            side_dirty = True
        # persist the best current partial (write-then-rename): the
        # SIGTERM handler flushes this file, and a hard kill still
        # leaves it on disk. Throttled to every ~10s once measurement
        # starts -- re-parsing the event log per iteration would make
        # the watch loop quadratic for at most 10s less staleness.
        if side_dirty and (not measuring or now - last_side >= 10.0):
            events = _read_events(path)
            if not measuring:
                measuring = any(
                    e.get("ev") in ("cold_iter", "warm_iter") for e in events
                )
            _write_side(_assemble_partial(events, "in progress"))
            last_side = now
            side_dirty = False
        if now - start > budget_s:
            why = f"budget exceeded ({budget_s:.0f}s)"
            proc.kill()
            proc.wait()
            break
        limit = stall_s if measuring else startup_stall
        if now - last_change > limit:
            why = f"no progress for {limit:.0f}s (tunnel stall)"
            proc.kill()
            proc.wait()
            break
        time.sleep(2.0)
    _WATCH["proc"], _WATCH["events_path"] = None, None
    events = _read_events(path)
    try:
        os.unlink(path)
    except OSError:
        pass
    result = next((e["out"] for e in events if e.get("ev") == "result"), None)
    err = next((e["error"] for e in events if e.get("ev") == "error"), None)
    if err and not why:
        why = err
    return result, events, why


def _assemble_partial(events: list, why: str):
    """Build the best completed-accelerator partial from child progress
    events (VERDICT round 3, item 1: a mid-run tunnel loss must emit the
    completed TPU iterations, not silently fall back to CPU). Completed-
    stage fields streamed via stage_fields events overlay the estimate:
    they carry the child's own computed stats for every stage that
    FINISHED, so a late kill loses only the stage in flight."""
    backend = next((e["backend"] for e in events if e.get("ev") == "backend"), None)
    cold = [e["ms"] for e in events if e.get("ev") == "cold_iter"]
    warm = [e["ms"] for e in events if e.get("ev") == "warm_iter"]
    gc2 = sum(e.get("gc2", 0) for e in events
              if e.get("ev") in ("cold_iter", "warm_iter"))
    fields: dict = {}
    for e in events:
        if e.get("ev") == "stage_fields":
            fields.update(e.get("fields", {}))
    sample, mode = (cold, "cold_pods_partial") if len(cold) >= 5 else (warm, "warm_partial")
    if len(sample) < 5 or backend is None:
        if fields and backend is not None:
            # no usable iteration stream (e.g. a warm-only run), but whole
            # stages completed: their fields ARE the partial
            out = {
                "metric": f"p99_scheduling_decision_latency_{N_PODS // 1000}k_pods",
                "value": 0.0, "unit": "ms", "vs_baseline": 0.0,
                "partial": True, "partial_reason": why[:300],
                "platform": backend,
                "claim_basis": f"{'cpu' if backend == 'cpu' else 'accelerator'}_stage_fields",
            }
            out.update(fields)
            return out
        return None
    arr = np.array(sample)
    p50, p99 = float(np.percentile(arr, 50)), float(np.percentile(arr, 99))
    out = {
        "metric": f"p99_scheduling_decision_latency_{N_PODS // 1000}k_pods",
        "value": round(p99, 2),
        "unit": "ms",
        "vs_baseline": round(TARGET_MS / p99, 3) if p99 > 0 else 0.0,
        "p50_ms": round(p50, 2),
        "mode": mode,
        "partial": True,
        "partial_reason": why[:300],
        "cold_iters_ms": [round(x, 1) for x in cold],
        "warm_iters_ms": [round(x, 1) for x in warm],
        "gc_gen2_during_measurement": gc2,
        "tail_ratio_p99_p50": round(p99 / p50, 3) if p50 > 0 else 0.0,
        "platform": backend,
        "claim_basis": (
            f"{'cpu' if backend == 'cpu' else 'accelerator'}"
            f"_partial_{len(sample)}_iters"
        ),
    }
    # completed-stage overlay: the child's own computed stats win over the
    # iteration-stream estimate for every stage that finished
    out.update(fields)
    return out


def _attach_capture(out: dict) -> dict:
    """Attach the committed mid-round TPU capture as provenance when the
    live run could not reach the accelerator (VERDICT round 3, weak #1:
    artifacts must carry the basis of the TPU claim)."""
    try:
        with open(CAPTURE_PATH) as f:
            cap = json.loads(f.read())
        cap["claim_basis"] = (
            "mid-round capture on the real accelerator, committed as "
            "BENCH_TPU_CAPTURE.json; live run degraded (see probe_error)"
        )
        # keep the artifact bounded: the capture's own iteration lists
        # are in the committed file
        cap.pop("cold_iters_ms", None)
        cap.pop("warm_iters_ms", None)
        out["tpu_capture"] = cap
    except (OSError, json.JSONDecodeError):
        pass
    return out


def main() -> None:
    if "--coldstart-child" in sys.argv:
        _coldstart_child()
        return
    if "--child" in sys.argv:
        _child_main()
        return
    profile = "--profile" in sys.argv
    force_cpu = "--cpu" in sys.argv

    # the WALL budget every stage clamps to: patience is still the policy
    # (the probe may wait a long time for a flaky tunnel), but the sum of
    # all stages must land the JSON line before any sane driver timeout --
    # round 5 lost its artifact to exactly this self-DoS (the probe's own
    # 2 h default exceeded the driver's timeout; rc 124, no line printed)
    wall_budget = _env_f("BENCH_WALL_BUDGET_S", 3300.0)
    t_wall = time.monotonic()

    # incremental persistence target (satellite): overridable for tests;
    # unique per run so a stale file can never masquerade as this run's
    import tempfile as _tempfile

    side = os.environ.get("BENCH_SIDE_PATH")
    if not side:
        fd, side = _tempfile.mkstemp(prefix="bench_partial_", suffix=".json")
        os.close(fd)
        os.unlink(side)  # the first _write_side re-creates it atomically
    _WATCH["side_path"] = side

    def remaining() -> float:
        return max(0.0, wall_budget - (time.monotonic() - t_wall))

    _install_sigterm_last_resort()

    degraded = False
    probe_err = None
    if force_cpu:
        backend, probe_err = None, "forced by --cpu"
    else:
        # PATIENT by default (VERDICT r4 item 1a): the tunnel has been
        # observed to drop for multi-hour stretches, so the probe waits
        # across many fixed-size attempts before falling back to CPU --
        # but never past its share of the wall budget (about 40%: the
        # measurement children must still fit behind it).
        backend, probe_err = probe_backend(
            timeout_s=_env_f("BENCH_PROBE_TIMEOUT_S", 150),
            attempts=int(_env_f("BENCH_PROBE_ATTEMPTS", 48)),
            backoff=1.0,
            budget_s=_clamped_budget(
                "BENCH_PROBE_BUDGET_S", 7200.0, remaining(), 0.6 * wall_budget
            ),
        )

    try:
        out = None
        if backend is not None:
            result, events, why = _run_child(
                force_cpu=False, profile=profile,
                # reserve enough of the wall for a CPU-fallback child
                # plus final assembly
                budget_s=_clamped_budget(
                    "BENCH_BUDGET_S", 1500.0, remaining(), 0.25 * wall_budget
                ),
                stall_s=_env_f("BENCH_STALL_S", 360),
            )
            if result is not None:
                out = result
                out.setdefault(
                    "claim_basis",
                    "tpu_measured" if result.get("platform") not in (None, "cpu")
                    else "cpu_measured",
                )
            else:
                out = _assemble_partial(events, why)
                if out is None:
                    degraded = True
                    probe_err = f"accelerator run produced no usable iterations: {why}"
        else:
            degraded = not force_cpu

        if out is None:
            # CPU fallback: bounded, and carrying the committed TPU capture
            # as the basis for the accelerator claim
            _WATCH["degraded"], _WATCH["probe_error"] = degraded, probe_err
            if degraded and probe_err:
                print(f"# accelerator unavailable, falling back to cpu: {probe_err}",
                      file=sys.stderr)
            result, events, why = _run_child(
                force_cpu=True, profile=profile,
                budget_s=_clamped_budget(
                    "BENCH_CPU_BUDGET_S", 2000.0, remaining(), 30.0
                ),
                stall_s=_env_f("BENCH_STALL_S", 360),
            )
            out = result if result is not None else _assemble_partial(events, why)
            if out is None:
                raise RuntimeError(f"cpu fallback failed: {why}")
            if degraded:
                out["degraded"] = True
                out["probe_error"] = (probe_err or "")[:300]
                out.setdefault("claim_basis", "cpu_degraded")
                _attach_capture(out)
        print(json.dumps(out))
    except Exception as e:  # noqa: BLE001 - the JSON line must always appear
        traceback.print_exc()
        err_out = {
            "metric": f"p99_scheduling_decision_latency_{N_PODS // 1000}k_pods",
            "value": 0.0,
            "unit": "ms",
            "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}"[:300],
            "degraded": True,
        }
        _attach_capture(err_out)
        print(json.dumps(err_out))
    if not os.environ.get("BENCH_SIDE_PATH"):
        # the run printed its line; the temp side file has served its
        # purpose (an explicit BENCH_SIDE_PATH is left for the caller)
        try:
            os.unlink(_WATCH["side_path"])
        except (OSError, TypeError):
            pass
    sys.stdout.flush()


if __name__ == "__main__":
    main()
