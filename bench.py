"""Scale benchmark: the BASELINE.json north-star measurement.

Measures end-to-end scheduling-decision latency for 50k pending pods against
the full instance-type catalog on one accelerator chip: pod classes encoded
(host), constraint masks + batched FFD solve (device), full decision
materialized (host) as one compact fetch. Reported as p99 over repeated
solves with varied workloads.

Note on transport: under the test harness the chip is reached through a
network tunnel with ~70 ms round-trip latency, which bounds e2e below by
one RTT (the solve is one async dispatch + one blocking fetch). The device
compute itself is ~9 ms/solve (see --profile's amortized number); deployed
on the TPU VM (the SURVEY.md section 7 architecture) the RTT term vanishes.

Target (BASELINE.md): < 100 ms p99 @ 50k pods x ~700 types.
The reference has no published number for this path -- its in-process Go FFD
is the implicit baseline and the 100 ms target is the contract; vs_baseline
reports target/measured (>1 means beating the target).

Usage: python bench.py            (one JSON line on stdout)
       python bench.py --profile  (extra breakdown on stderr)
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


N_PODS = 50_000
N_CLASS_SHAPES = 192
C_PAD = 192
G_MAX = 512
NNZ_MAX = 4096
ITERS = 100
WARMUP = 5


def build_catalog_items():
    from karpenter_tpu.apis import TPUNodeClass
    from karpenter_tpu.apis.nodeclass import SubnetStatus
    from karpenter_tpu.cache.unavailable_offerings import UnavailableOfferings
    from karpenter_tpu.kwok.cloud import FakeCloud
    from karpenter_tpu.providers.instancetype import gen_catalog
    from karpenter_tpu.providers.instancetype.offerings import OfferingsBuilder
    from karpenter_tpu.providers.instancetype.provider import InstanceTypeProvider
    from karpenter_tpu.providers.instancetype.types import Resolver
    from karpenter_tpu.providers.pricing import PricingProvider

    cloud = FakeCloud()
    prov = InstanceTypeProvider(
        cloud,
        Resolver(gen_catalog.REGION),
        OfferingsBuilder(
            PricingProvider(cloud, cloud, gen_catalog.REGION),
            UnavailableOfferings(),
            {z.name: z.zone_id for z in cloud.describe_zones()},
        ),
        UnavailableOfferings(),
    )
    nc = TPUNodeClass("default")
    nc.status_subnets = [SubnetStatus(s.id, s.zone, s.zone_id) for s in cloud.describe_subnets()]
    return prov.list(nc)


def synth_workload(rng: np.random.Generator, catalog, n_pods: int):
    """A 50k-pod pending set, pre-grouped into classes (the controller's
    batching window produces exactly this shape). Mix modeled on scale-test
    workloads: mostly small web pods, some medium services, a few large."""
    from karpenter_tpu.solver import encode
    from karpenter_tpu.apis import labels as wk
    from karpenter_tpu.scheduling import Requirements

    C = N_CLASS_SHAPES
    cpu_choices = np.array([100, 100, 250, 250, 500, 500, 1000, 2000, 4000, 8000])
    mem_choices = np.array([128, 256, 512, 512, 1024, 2048, 4096, 8192, 16384, 32768])
    idx = rng.integers(0, len(cpu_choices), size=C)
    weights = rng.dirichlet(np.ones(C) * 0.5)
    counts = np.maximum(1, (weights * n_pods).astype(np.int64))
    counts[0] += n_pods - counts.sum()

    req = np.zeros((C, encode.R), dtype=np.float32)
    import karpenter_tpu.scheduling.resources as res

    req[:, res.AXIS_INDEX[res.CPU]] = cpu_choices[idx]
    req[:, res.AXIS_INDEX[res.MEMORY]] = mem_choices[idx]  # MiB (already scaled units)
    req[:, res.AXIS_INDEX[res.PODS]] = 1.0

    # sort FFD-style: dominant resource desc
    order = np.lexsort((-req[:, res.AXIS_INDEX[res.MEMORY]], -req[:, res.AXIS_INDEX[res.CPU]]))
    req = req[order]
    counts = counts[order]

    c_pad = C_PAD
    empty = Requirements()
    allowed = [np.zeros((c_pad, w), dtype=np.uint32) for w in catalog.words]
    for d in range(encode.D):
        allowed[d][:] = 0xFFFFFFFF
    num_lo = np.full((c_pad, encode.ND), -np.inf, dtype=np.float32)
    num_hi = np.full((c_pad, encode.ND), np.inf, dtype=np.float32)
    azone = np.zeros((c_pad, encode.Z_PAD), dtype=bool)
    azone[:, : len(catalog.zones)] = True
    acap = np.zeros((c_pad, encode.CT), dtype=bool)
    acap[:] = True
    # a third of classes are zone-pinned / captype-constrained (constraint
    # masks exercise the requirement path)
    zone_pin = rng.random(c_pad) < 0.2
    azone[zone_pin] = False
    azone[zone_pin, rng.integers(0, len(catalog.zones), size=int(zone_pin.sum()))] = True
    od_only = rng.random(c_pad) < 0.15
    acap[od_only, 1] = False  # no spot

    reqp = np.zeros((c_pad, encode.R), dtype=np.float32)
    reqp[:C] = req
    countp = np.zeros((c_pad,), dtype=np.int32)
    countp[:C] = counts
    sched = np.zeros((c_pad,), dtype=bool)
    sched[:C] = True

    cs = encode.PodClassSet(
        classes=[], c_real=C, c_pad=c_pad, req=reqp, count=countp, allowed=allowed,
        num_lo=num_lo, num_hi=num_hi, azone=azone, acap=acap, schedulable=sched,
    )
    return cs


def main() -> None:
    profile = "--profile" in sys.argv
    use_pallas = "--pallas" in sys.argv  # measure the fused pallas step kernel
    import jax

    from karpenter_tpu.solver import encode, ffd

    if use_pallas and jax.default_backend() != "tpu":
        print(
            "# --pallas off-TPU runs the INTERPRETER (orders of magnitude "
            "slower than either real lowering); timings below are not the "
            "kernel's", file=sys.stderr,
        )

    t0 = time.perf_counter()
    items = build_catalog_items()
    catalog = encode.encode_catalog(items)
    # catalog tensors are staged on device ONCE (they change on the 12h
    # refresh cadence, not per scheduling tick -- SURVEY.md section 7 hard
    # part #6); per-solve traffic is the pod-class tensors only
    staged, offsets, words = ffd.stage_catalog(catalog)
    t_catalog = time.perf_counter() - t0

    rng = np.random.default_rng(42)
    workloads = [synth_workload(rng, catalog, N_PODS) for _ in range(8)]

    def solve(cs):
        inp = ffd.make_inputs_staged(staged, cs)
        out = ffd.ffd_solve_packed(
            inp, staged.price, g_max=G_MAX, nnz_max=NNZ_MAX,
            word_offsets=offsets, words=words, use_pallas=use_pallas,
        )
        # materialize the full decision -- sparse placements, leftovers,
        # and per-group offering selection -- in one device->host fetch
        dec = jax.device_get(out)
        assert int(dec.nnz) <= NNZ_MAX, "sparse take overflow; refetch dense"
        return dec

    # warmup / compile
    t0 = time.perf_counter()
    dec = solve(workloads[0])
    t_compile = time.perf_counter() - t0
    n_open = int(dec.n_open)
    placed = int(dec.val.sum())
    assert placed + int(dec.unplaced.sum()) == int(workloads[0].count.sum()), "pod conservation violated"
    # adaptive warmup: the chip sits behind a network tunnel whose first
    # seconds after idle can be pathologically slow (seconds per solve);
    # warm until solve time stabilizes near its observed floor so the
    # measurement reflects steady state, not transport cold-start
    best = float("inf")
    stable = 0
    for _ in range(60):
        t0 = time.perf_counter()
        solve(workloads[0])
        dt = time.perf_counter() - t0
        if dt < best * 0.9:
            stable = 0  # still improving markedly: not yet at steady state
        elif dt <= best * 1.3:
            stable += 1
            if stable >= WARMUP:
                break
        else:
            stable = 0
        best = min(best, dt)

    times = []
    for i in range(ITERS):
        cs = workloads[i % len(workloads)]
        t0 = time.perf_counter()
        solve(cs)
        times.append((time.perf_counter() - t0) * 1000.0)
    times = np.array(times)
    p50, p99 = float(np.percentile(times, 50)), float(np.percentile(times, 99))

    if profile:
        # amortized device-compute time: N dependent dispatches, one block
        # (subtracts the transport RTT that dominates single-solve e2e)
        inp = ffd.make_inputs_staged(staged, workloads[0])
        n_amort = 20
        t0 = time.perf_counter()
        for _ in range(n_amort):
            out = ffd.ffd_solve_packed(
                inp, staged.price, g_max=G_MAX, nnz_max=NNZ_MAX,
                word_offsets=offsets, words=words, use_pallas=use_pallas,
            )
        jax.block_until_ready(out)
        t_amort = (time.perf_counter() - t0) * 1e3
        print(
            f"# catalog build {t_catalog*1e3:.0f}ms; first solve (compile) {t_compile:.1f}s; "
            f"p50 {p50:.1f}ms p99 {p99:.1f}ms min {times.min():.1f}ms max {times.max():.1f}ms; "
            f"device-only ~{t_amort/n_amort:.1f}ms/solve; "
            f"nodes opened {n_open}; pods placed {placed}/{N_PODS}; backend {jax.default_backend()}",
            file=sys.stderr,
        )
    print(
        json.dumps(
            {
                "metric": f"p99_scheduling_decision_latency_{N_PODS//1000}k_pods_{catalog.k_real}_types",
                "value": round(p99, 2),
                "unit": "ms",
                "vs_baseline": round(100.0 / p99, 3) if p99 > 0 else 0.0,
            }
        )
    )


if __name__ == "__main__":
    main()
